"""Tier-1 host-loop smoke: the LIVE server loop — broker dequeue → worker
snapshot-sync → stack select → coalescer → plan queue → batched applier —
must place a job burst above a conservative throughput floor under the
fake-device backend (NOMAD_TPU_FAKE_DEVICE=1).

The floor is deliberately ~10x below the measured rate (~600 evals/s at
2000 nodes, tools/host_loop_profile.txt) so the test never flakes on a
loaded CI box, while still catching a reversion to the pre-overhaul
regime (~5 evals/s through the real dispatch path, ~78 evals/s under the
fake device before the host-path work)."""

from __future__ import annotations

import time

import numpy as np

from nomad_tpu import mock
from nomad_tpu.server.server import Server, ServerConfig

N_NODES = 200
N_JOBS = 128
FLOOR_EVALS_PER_SEC = 50.0

MEGABATCH_B = 256
MEGABATCH_FLOOR = 3.0


def test_host_loop_burst_above_floor(monkeypatch):
    monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE", "1")
    srv = Server(ServerConfig(
        num_workers=4,
        node_capacity=256,
        heartbeat_min_ttl=3600.0,
        heartbeat_max_ttl=7200.0,
    ))
    srv.start()
    try:
        for i in range(N_NODES):
            node = mock.node()
            node.node_class = f"class-{i % 6}"
            srv.register_node(node)

        def make_job(i: int):
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 2
            tg.tasks[0].resources.cpu = 50 + 25 * (i % 4)
            tg.tasks[0].resources.memory_mb = 64 + 32 * (i % 3)
            return job

        # Warm the select path outside the timed region.
        ev = srv.submit_job(make_job(0))
        assert srv.wait_for_eval(ev.id, timeout=60.0)

        t0 = time.time()
        evals = [srv.submit_job(make_job(i)) for i in range(N_JOBS)]
        pending = {e.id for e in evals}
        deadline = time.time() + 60.0
        last_index = 0
        while pending and time.time() < deadline:
            pending = {
                eid for eid in pending
                if not (
                    (e := srv.store.eval_by_id(eid)) is not None
                    and e.terminal_status()
                )
            }
            if not pending:
                break
            last_index = srv.store.wait_for_table(
                "evals", last_index, timeout=0.25
            )
        wall = time.time() - t0

        assert not pending, f"{len(pending)} evals never went terminal"
        rate = N_JOBS / wall
        assert rate >= FLOOR_EVALS_PER_SEC, (
            f"host loop placed {N_JOBS} evals at {rate:.1f}/s — below the "
            f"{FLOOR_EVALS_PER_SEC}/s floor (pre-overhaul regression?)"
        )
        # The burst must have actually placed allocs, not failed them.
        n_allocs = len(srv.store.allocs)
        assert n_allocs >= N_JOBS, (
            f"only {n_allocs} allocs for {N_JOBS} jobs x count=2"
        )
    finally:
        srv.shutdown()


def test_megabatch_throughput_floor():
    """Tier-1 CI gate: the mega-batched fused kernel must process a B=256
    eval batch ≥ 3× faster than the staged per-eval dispatch path it
    replaced, on the CPU backend CI runs on.

    Measured on the real (JAX CPU) kernels because the win being gated is
    launch amortization — one fused launch vs 256 per-eval dispatches.
    The NOMAD_TPU_FAKE_DEVICE numpy twin is a per-lane loop by design
    (same compute either way — its parity is pinned in
    tests/test_megakernel.py), so it cannot observe this regression.
    Headroom is real: measured ~8× on an idle box; 3× is the flake-proof
    floor."""
    import jax
    import jax.numpy as jnp

    from nomad_tpu.ops import RequestEncoder, kernels, place_task_group
    from nomad_tpu.ops.encode import MAX_SPREADS, MAX_SPREAD_VALUES
    from nomad_tpu.state import NodeMatrix
    from nomad_tpu.structs import (
        DriverInfo, Job, Node, NodeResources, Resources, Task, TaskGroup,
    )

    m = NodeMatrix(capacity=256)
    for i in range(N_NODES):
        m.upsert_node(Node(
            datacenter="dc1",
            resources=NodeResources(cpu=4000 + 10 * i, memory_mb=8192,
                                    disk_mb=100 * 1024),
            drivers={"mock": DriverInfo()},
        ))

    def make_job(i: int) -> Job:
        tg = TaskGroup(name="web", count=1, tasks=[Task(resources=Resources(
            cpu=50 + 25 * (i % 4), memory_mb=64 + 32 * (i % 3)))])
        return Job(task_groups=[tg])

    enc = RequestEncoder(m)
    compiled = [
        enc.compile(make_job(i), make_job(i).task_groups[0])
        for i in range(MEGABATCH_B)
    ]
    arrays = m.sync()
    n = int(arrays.used.shape[0])
    feats = kernels.features_of(compiled[0].request)
    for c in compiled[1:]:
        feats = feats.widen(kernels.features_of(c.request))

    tg0 = jnp.zeros((n,), jnp.int32)
    sc0 = jnp.zeros((MAX_SPREADS, MAX_SPREAD_VALUES), jnp.float32)
    pen0 = jnp.zeros((n,), bool)
    ce0 = jnp.ones((2,), bool)
    hm0 = jnp.ones((n,), bool)

    def staged_per_eval():
        rows = []
        for c in compiled:
            r = place_task_group(arrays, c.request, arrays.used, tg0, sc0,
                                 pen0, ce0, hm0, 1, features=feats)
            rows.append(np.asarray(r.rows))
        return rows

    reqs = jax.tree_util.tree_map(
        lambda *xs: np.stack(xs), *[c.request for c in compiled]
    )
    B = MEGABATCH_B
    dr = jnp.full((B, 1), -1, jnp.int32)
    dv = jnp.zeros((B, 1, 3), jnp.float32)
    tgb = jnp.zeros((B, n), jnp.int32)
    scb = jnp.zeros((B, MAX_SPREADS, MAX_SPREAD_VALUES), jnp.float32)
    penb = jnp.zeros((B, n), bool)
    ceb = jnp.ones((B, 2), bool)
    hmb = jnp.ones((B, n), bool)
    lm = jnp.ones((B,), bool)

    def fused_batch():
        return np.asarray(kernels.fused_place_batch(
            arrays, arrays.used, dr, dv, tgb, scb, penb, reqs, ceb, hmb,
            lm, n_placements=1, features=feats,
        ))

    # Warm both paths out of the timed region (compile + first transfer),
    # then take the best of 3 so a CI scheduling hiccup on one rep can't
    # fail the gate.
    staged_rows = staged_per_eval()
    fused_out = fused_batch()

    staged_s = min(_timed(staged_per_eval) for _ in range(3))
    fused_s = min(_timed(fused_batch) for _ in range(3))
    ratio = staged_s / fused_s

    # Both paths must have placed the same nodes (sanity, not the gate).
    np.testing.assert_array_equal(
        fused_out[:, 0, 0].astype(np.int32),
        np.concatenate(staged_rows).astype(np.int32),
    )
    assert ratio >= MEGABATCH_FLOOR, (
        f"fused megakernel processed B={B} at only {ratio:.2f}x the staged "
        f"per-eval path ({staged_s * 1e6 / B:.0f} -> {fused_s * 1e6 / B:.0f} "
        f"us/eval) — below the {MEGABATCH_FLOOR}x floor"
    )


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0
