"""Tier-1 host-loop smoke: the LIVE server loop — broker dequeue → worker
snapshot-sync → stack select → coalescer → plan queue → batched applier —
must place a job burst above a conservative throughput floor under the
fake-device backend (NOMAD_TPU_FAKE_DEVICE=1).

The floor is deliberately ~10x below the measured rate (~600 evals/s at
2000 nodes, tools/host_loop_profile.txt) so the test never flakes on a
loaded CI box, while still catching a reversion to the pre-overhaul
regime (~5 evals/s through the real dispatch path, ~78 evals/s under the
fake device before the host-path work)."""

from __future__ import annotations

import time

from nomad_tpu import mock
from nomad_tpu.server.server import Server, ServerConfig

N_NODES = 200
N_JOBS = 128
FLOOR_EVALS_PER_SEC = 50.0


def test_host_loop_burst_above_floor(monkeypatch):
    monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE", "1")
    srv = Server(ServerConfig(
        num_workers=4,
        node_capacity=256,
        heartbeat_min_ttl=3600.0,
        heartbeat_max_ttl=7200.0,
    ))
    srv.start()
    try:
        for i in range(N_NODES):
            node = mock.node()
            node.node_class = f"class-{i % 6}"
            srv.register_node(node)

        def make_job(i: int):
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 2
            tg.tasks[0].resources.cpu = 50 + 25 * (i % 4)
            tg.tasks[0].resources.memory_mb = 64 + 32 * (i % 3)
            return job

        # Warm the select path outside the timed region.
        ev = srv.submit_job(make_job(0))
        assert srv.wait_for_eval(ev.id, timeout=60.0)

        t0 = time.time()
        evals = [srv.submit_job(make_job(i)) for i in range(N_JOBS)]
        pending = {e.id for e in evals}
        deadline = time.time() + 60.0
        last_index = 0
        while pending and time.time() < deadline:
            pending = {
                eid for eid in pending
                if not (
                    (e := srv.store.eval_by_id(eid)) is not None
                    and e.terminal_status()
                )
            }
            if not pending:
                break
            last_index = srv.store.wait_for_table(
                "evals", last_index, timeout=0.25
            )
        wall = time.time() - t0

        assert not pending, f"{len(pending)} evals never went terminal"
        rate = N_JOBS / wall
        assert rate >= FLOOR_EVALS_PER_SEC, (
            f"host loop placed {N_JOBS} evals at {rate:.1f}/s — below the "
            f"{FLOOR_EVALS_PER_SEC}/s floor (pre-overhaul regression?)"
        )
        # The burst must have actually placed allocs, not failed them.
        n_allocs = len(srv.store.allocs)
        assert n_allocs >= N_JOBS, (
            f"only {n_allocs} allocs for {N_JOBS} jobs x count=2"
        )
    finally:
        srv.shutdown()
