"""Round-4 regression tests for the round-2/3 advisor findings (VERDICT
item 3): each of these failed on the pre-fix HEAD.

1. WAL entries sharing an index with a mid-batch snapshot were dropped on
   restore (no per-entry sequence) — a GC-deleted eval resurrected.
2. The drainer completed a node's drain while system allocs still ran
   (reference stops RemainingAllocs first, drainer/watch_nodes.go:91-101).
3. A restored deployment alloc never started its health watcher, stalling
   or falsely reverting the deployment.
4. Store mutators stamped time.time() during apply, making WAL replay
   non-deterministic (timestamps are now journaled args).
5. The event broker silently replayed a gapped backlog when from_index
   predated the ring (no signal to the consumer).
"""

from __future__ import annotations

import json
import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.state.store import StateStore
from nomad_tpu.state.wal import WriteAheadLog
from nomad_tpu.stream import Event, EventBroker
from nomad_tpu.structs.types import (
    AllocClientStatus,
    DeploymentStatus,
    DrainStrategy,
    Evaluation,
    NodeStatus,
    Task,
    UpdateStrategy,
)


from helpers import _client, _crash_client, _small, _wait  # noqa: E402


@pytest.fixture
def server():
    s = Server(ServerConfig(
        num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90
    ))
    s.start()
    yield s
    s.shutdown()


# ----------------------------------------------------------------------
# 1. WAL: same-index entries across a snapshot cut survive restore
# ----------------------------------------------------------------------


def test_wal_same_index_entry_after_snapshot_replays(tmp_path):
    wal = WriteAheadLog(str(tmp_path))
    wal.append(5, "op_a", {"args": [], "kwargs": {}})
    wal.write_snapshot({"latest_index": 5})
    wal.append(5, "op_b", {"args": [], "kwargs": {}})
    wal.close()

    wal2 = WriteAheadLog(str(tmp_path))
    snap, entries = wal2.load()
    assert snap["latest_index"] == 5
    # op_b shares the snapshot's index but came after it — it MUST replay.
    assert [e["op"] for e in entries] == ["op_b"]
    # The sequence resumes past everything on disk.
    assert wal2.seq >= 2


def test_gc_deleted_eval_does_not_resurrect(tmp_path):
    """The advisor's repro: delete journaled at the snapshot's index was
    dropped on restore, resurrecting the eval."""
    wal = WriteAheadLog(str(tmp_path))
    store = StateStore()
    store.attach_wal(wal)
    ev = Evaluation(job_id="j1")
    store.upsert_evals(7, [ev])
    store.write_snapshot()
    store.delete_eval(7, ev.id)  # same raft index as the snapshot cut
    wal.close()

    wal2 = WriteAheadLog(str(tmp_path))
    store2 = StateStore()
    store2.restore(*wal2.load())
    assert store2.eval_by_id(ev.id) is None


# ----------------------------------------------------------------------
# 2. Drainer: system allocs stopped before drain completes
# ----------------------------------------------------------------------


def test_drain_pass_holds_completion_for_system_allocs():
    """Unit repro of the ordering bug: a drain pass over a node whose only
    live work is a system alloc must stamp that alloc and NOT complete the
    drain (pre-fix it completed immediately, leaving the alloc running on
    an 'undrained' node if the eval path was slow or lost)."""
    from nomad_tpu.server.drainer import NodeDrainer
    from nomad_tpu.structs.types import Allocation

    store = StateStore()

    class FakeServer:
        def __init__(self):
            self.store = store
            self.completed = []
            self.transitions = {}

        def complete_node_drain(self, node_id):
            self.completed.append(node_id)

        def apply_alloc_desired_transitions(self, transitions, evals):
            self.transitions.update(transitions)
            store.update_allocs_desired_transition(
                store.latest_index + 1, transitions
            )

    srv = FakeServer()
    node = mock.node()
    node.drain = True
    node.drain_strategy = DrainStrategy(
        deadline=300.0, force_deadline=time.time() + 300.0
    )
    store.upsert_node(1, node)
    sysjob = mock.system_job()
    alloc = Allocation(
        job_id=sysjob.id, namespace=sysjob.namespace, job=sysjob,
        node_id=node.id, task_group=sysjob.task_groups[0].name,
        client_status=AllocClientStatus.RUNNING.value,
    )
    store.upsert_allocs(2, [alloc])

    drainer = NodeDrainer(srv)
    drainer._drain_pass([store.node_by_id(node.id)])
    assert srv.completed == [], "drain completed with a live system alloc"
    assert alloc.id in srv.transitions, "system alloc was never stamped"

    # Once the system alloc is stopped, the next pass completes the drain.
    stopped = alloc.copy()
    stopped.client_status = AllocClientStatus.COMPLETE.value
    stopped.desired_status = "stop"
    store.upsert_allocs(3, [stopped])
    drainer._drain_pass([store.node_by_id(node.id)])
    assert srv.completed == [node.id]


def test_drain_stops_system_allocs_before_completing(server, tmp_path):
    c1 = _client(server, tmp_path, "c1")
    c2 = _client(server, tmp_path, "c2")
    try:
        sysjob = _small(mock.system_job())
        sysjob.task_groups[0].tasks[0].config = {"run_for": 600}
        ev = server.submit_job(sysjob)
        server.wait_for_eval(ev.id, timeout=90)
        assert _wait(lambda: len([
            a for a in server.store.allocs_by_job(sysjob.namespace, sysjob.id)
            if a.client_status == AllocClientStatus.RUNNING.value
        ]) == 2, timeout=60)

        target = c1.node.id
        server.update_node_drain(
            target,
            DrainStrategy(deadline=300.0, force_deadline=time.time() + 300.0),
        )
        server.drainer.notify()

        # Drain must complete — and when it does, no system alloc may
        # still be live on the node (pre-fix: drain completed instantly
        # with the system alloc still running).
        assert _wait(
            lambda: not server.store.node_by_id(target).drain, timeout=60
        )
        live = [
            a for a in server.store.allocs_by_node(target)
            if not a.terminal_status()
        ]
        assert live == [], [
            (a.job_id, a.client_status, a.desired_status) for a in live
        ]
        # The other node's system alloc is untouched.
        assert [
            a for a in server.store.allocs_by_node(c2.node.id)
            if not a.terminal_status()
        ]
    finally:
        c1.shutdown()
        c2.shutdown()


# ----------------------------------------------------------------------
# 3. Restored deployment alloc reports health
# ----------------------------------------------------------------------


def test_restored_alloc_reports_deployment_health(server, tmp_path):
    data_dir = str(tmp_path / "client")
    c1 = Client(server, ClientConfig(data_dir=data_dir))
    c1.start()

    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 1
    tg.tasks = [Task(
        name="main", driver="raw_exec",
        config={"command": "/bin/sleep", "args": ["300"]},
    )]
    _small(job)
    tg.update = UpdateStrategy(
        max_parallel=1, min_healthy_time=4.0, healthy_deadline=45.0,
        progress_deadline=60.0,
    )
    ev = server.submit_job(job)
    server.wait_for_eval(ev.id, timeout=60)
    assert _wait(lambda: [
        a for a in server.store.allocs_by_job(job.namespace, job.id)
        if a.client_status == AllocClientStatus.RUNNING.value
    ], timeout=60)

    # Destructive update → deployment gating on alloc health.
    job2 = job.copy()
    job2.task_groups[0].tasks[0].env = {"V": "2"}
    ev2 = server.submit_job(job2)
    server.wait_for_eval(ev2.id, timeout=60)

    def v1_running():
        return [
            a for a in server.store.allocs_by_job(job.namespace, job.id)
            if a.client_status == AllocClientStatus.RUNNING.value
            and a.deployment_id
            and a.job is not None and a.job.version == 1
        ]
    assert _wait(lambda: v1_running(), timeout=60)
    alloc = v1_running()[0]
    # Crash before min_healthy_time elapses: health not yet reported.
    assert (
        alloc.deployment_status is None
        or alloc.deployment_status.healthy is None
    )
    _crash_client(c1)

    c2 = Client(server, ClientConfig(data_dir=data_dir))
    c2.start()
    try:
        # The restored alloc must resume health watching and drive the
        # deployment to success (pre-fix: stalls, then fails/reverts).
        def dep_successful():
            d = server.store.latest_deployment_by_job(job.namespace, job.id)
            return (
                d is not None
                and d.job_version == 1
                and d.status == DeploymentStatus.SUCCESSFUL.value
            )
        assert _wait(dep_successful, timeout=40), (
            server.store.latest_deployment_by_job(job.namespace, job.id)
        )
    finally:
        c2.shutdown()


# ----------------------------------------------------------------------
# 4. Deterministic replay: timestamps are journaled, not re-stamped
# ----------------------------------------------------------------------


def test_replay_preserves_wallclock_stamps(tmp_path):
    wal = WriteAheadLog(str(tmp_path))
    store = StateStore()
    store.attach_wal(wal)
    node = mock.node()
    store.upsert_node(1, node)
    store.update_node_status(2, node.id, NodeStatus.DOWN.value)
    stamped = store.node_by_id(node.id).status_updated_at
    assert stamped > 0
    wal.close()

    # The timestamp travels inside the journaled entry...
    with open(os.path.join(str(tmp_path), "wal.jsonl")) as fh:
        entries = [json.loads(line) for line in fh]
    status_entries = [e for e in entries if e["op"] == "update_node_status"]
    assert status_entries and (
        status_entries[0]["a"]["kwargs"].get("now") == stamped
    )

    # ...so replay at a later wall-clock reproduces it exactly.
    time.sleep(0.05)
    wal2 = WriteAheadLog(str(tmp_path))
    store2 = StateStore()
    store2.restore(*wal2.load())
    assert store2.node_by_id(node.id).status_updated_at == stamped


# ----------------------------------------------------------------------
# Blocked evals unblock when an in-process client registers (the client
# mutated the shared Node object before the ready-status update, so the
# server never saw an init→ready transition and skipped _capacity_added)
# ----------------------------------------------------------------------


def test_blocked_evals_unblock_on_client_registration(server, tmp_path):
    jobs = [_small(mock.job()) for _ in range(3)]
    for j in jobs:
        j.task_groups[0].count = 2
    evals = [server.submit_job(j) for j in jobs]
    for ev in evals:
        server.wait_for_eval(ev.id, timeout=60)
    assert server.blocked_evals.blocked_count() == 3

    c = _client(server, tmp_path, "c1")
    try:
        assert _wait(lambda: all(
            len(server.store.allocs_by_job(j.namespace, j.id)) > 0
            for j in jobs
        ), timeout=30), f"blocked={server.blocked_evals.blocked_count()}"
    finally:
        c.shutdown()


# ----------------------------------------------------------------------
# 5. Event stream: gapped backlog is signalled, not silent
# ----------------------------------------------------------------------


def test_subscribe_signals_backlog_gap():
    b = EventBroker(buffer_size=4)
    b.publish([
        Event(topic="Job", type="JobRegistered", key=f"j{i}", index=i)
        for i in range(1, 11)
    ])
    # Ring holds 7..10; indexes 1..6 were dropped.
    sub = b.subscribe({"Job": ["*"]}, from_index=2)
    events = sub.next(timeout=1.0)
    assert events, "expected gap marker + backlog"
    assert events[0].topic == "Framework"
    assert events[0].type == "EventStreamGap"
    assert events[0].payload["requested_index"] == 2
    assert events[0].payload["dropped_through"] == 6
    assert [e.index for e in events[1:]] == [7, 8, 9, 10]
    sub.close()


def test_subscribe_no_gap_when_backlog_complete():
    b = EventBroker(buffer_size=8)
    b.publish([
        Event(topic="Job", type="JobRegistered", key=f"j{i}", index=i)
        for i in range(1, 6)
    ])
    sub = b.subscribe({"Job": ["*"]}, from_index=2)
    events = sub.next(timeout=1.0)
    assert [e.index for e in events] == [3, 4, 5]
    assert all(e.type != "EventStreamGap" for e in events)
    sub.close()
