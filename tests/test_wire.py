"""Real wire boundary (VERDICT #6): server agent and client agent as two
separate OS processes, talking only over HTTP — node registration,
heartbeats, the blocking-query alloc watch, and batched status updates all
cross a real socket (reference seam: client/client.go:1997 dialing
Node.GetClientAllocs, nomad/node_endpoint.go:915)."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SERVER_SCRIPT = r"""
import sys, time
sys.path.insert(0, {repo!r})
import __graft_entry__
__graft_entry__._scrub_non_cpu_backends()
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.server.server import ServerConfig

agent = Agent(AgentConfig(
    client_enabled=False,
    server_config=ServerConfig(
        num_workers=1, node_capacity=32,
        heartbeat_min_ttl=2.0, heartbeat_max_ttl=3.0,
    ),
))
agent.start()
print("ADDR", agent.rpc_addr, flush=True)
while True:
    time.sleep(1)
"""

CLIENT_SCRIPT = r"""
import sys, time
sys.path.insert(0, {repo!r})
import __graft_entry__
__graft_entry__._scrub_non_cpu_backends()
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.client import ClientConfig

agent = Agent(AgentConfig(
    server_enabled=False,
    client_enabled=True,
    server_addr={addr!r},
    client_config=ClientConfig(data_dir={data_dir!r}),
))
agent.start()
print("NODE", agent.client.node.id, flush=True)
while True:
    time.sleep(1)
"""


def _spawn(code: str):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-u", "-c", code],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
        cwd=REPO,
    )


def _readline_tagged(proc, tag: str, timeout: float = 60.0) -> str:
    deadline = time.time() + timeout
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith(tag):
            return line.split(None, 1)[1].strip()
    err = proc.stderr.read() if proc.poll() is not None else ""
    raise AssertionError(f"never saw {tag!r}; stderr:\n{err}")


def _api(addr: str, path: str, body=None, method=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        addr + path, data=data,
        method=method or ("POST" if data else "GET"),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read() or b"null")


def _wait(pred, timeout=60.0, every=0.2):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(every)
    return pred()


@pytest.fixture
def two_process_cluster(tmp_path):
    server = _spawn(SERVER_SCRIPT.format(repo=REPO))
    procs = [server]
    try:
        addr = _readline_tagged(server, "ADDR")
        client = _spawn(CLIENT_SCRIPT.format(
            repo=REPO, addr=addr, data_dir=str(tmp_path / "client")
        ))
        procs.append(client)
        node_id = _readline_tagged(client, "NODE")
        yield addr, node_id, client
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=15)


def test_full_lifecycle_across_processes(two_process_cluster):
    addr, node_id, client_proc = two_process_cluster

    # Node registered + ready via the wire.
    assert _wait(lambda: _api(addr, f"/v1/node/{node_id}")["status"]
                 == "ready")

    # Submit a job through the public API; it must run on the remote client.
    job_payload = {
        "id": "wire-job",
        "name": "wire-job",
        "type": "service",
        "datacenters": ["dc1"],
        "task_groups": [{
            "name": "g",
            "count": 2,
            "tasks": [{
                "name": "t",
                "driver": "mock",
                "resources": {"cpu": 20, "memory_mb": 32},
            }],
            "ephemeral_disk": {"size_mb": 10},
        }],
    }
    out = _api(addr, "/v1/jobs", {"Job": job_payload})
    assert out["EvalID"]

    def running():
        allocs = _api(addr, "/v1/job/wire-job/allocations")
        return len([a for a in allocs
                    if a["client_status"] == "running"]) == 2
    assert _wait(running, timeout=90), _api(
        addr, "/v1/job/wire-job/allocations"
    )
    allocs = _api(addr, "/v1/job/wire-job/allocations")
    assert all(a["node_id"] == node_id for a in allocs)

    # Stop the job; the remote client must wind the tasks down.
    _api(addr, "/v1/job/wire-job", method="DELETE")

    def stopped():
        allocs = _api(addr, "/v1/job/wire-job/allocations")
        return all(a["client_status"] in ("complete", "failed")
                   for a in allocs)
    assert _wait(stopped, timeout=90)

    # Kill the client process: heartbeats stop; the server marks the node
    # down (TTL 2-3s) — failure detection over the wire.
    client_proc.kill()
    client_proc.wait(timeout=15)
    assert _wait(
        lambda: _api(addr, f"/v1/node/{node_id}")["status"] == "down",
        timeout=30,
    )


def test_rpc_proxy_blocking_query(two_process_cluster):
    """The alloc watch blocking query must actually block server-side
    (not poll): a no-change call with a short wait returns after ~wait."""
    addr, node_id, _ = two_process_cluster
    from nomad_tpu.api.rpc import HTTPServerRPC

    rpc = HTTPServerRPC(addr)
    allocs, index = rpc.get_client_allocs(node_id, min_index=0, timeout=1.0)
    assert allocs == []
    t0 = time.time()
    allocs2, index2 = rpc.get_client_allocs(
        node_id, min_index=index, timeout=2.0
    )
    elapsed = time.time() - t0
    assert elapsed >= 1.0, f"returned too fast ({elapsed:.2f}s) — not blocking"
    assert index2 >= index
