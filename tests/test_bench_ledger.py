"""Bench regression ledger gates: an injected 2x latency regression
must flag `regress`, noise within 1 MAD must stay `flat`, and the
committed BENCH_*.json files must ingest without error."""

from __future__ import annotations

import glob
import json
import os
import sys

import pytest

TOOLS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "tools")
REPO = os.path.dirname(TOOLS)
sys.path.insert(0, TOOLS)

import bench_history  # noqa: E402


def _seed_ledger(path, metric, values):
    for v in values:
        bench_history.append_entry(str(path), {
            "ts": 0.0, "source": "seed", "ok": True,
            "metrics": {metric: v}, "meta": {},
        })


class TestVerdicts:
    def test_2x_latency_regression_flags_regress(self, tmp_path):
        ledger = tmp_path / "ledger.jsonl"
        # Tight baseline around 100ms (MAD 1ms), then a 2x run.
        _seed_ledger(ledger, "e2e_p99_ms",
                     [99.0, 100.0, 101.0, 100.0, 99.5, 100.5])
        entry = bench_history.record_run(
            {"e2e_p99_ms": 200.0}, source="test", ledger=str(ledger))
        v = entry["verdicts"]["e2e_p99_ms"]
        assert v["verdict"] == "regress", v
        assert v["deviation"] == pytest.approx(100.0)

    def test_2x_throughput_drop_flags_regress(self, tmp_path):
        ledger = tmp_path / "ledger.jsonl"
        _seed_ledger(ledger, "eval_throughput",
                     [980.0, 1000.0, 1020.0, 1000.0])
        entry = bench_history.record_run(
            {"eval_throughput": 500.0}, source="test", ledger=str(ledger))
        assert entry["verdicts"]["eval_throughput"]["verdict"] == "regress"

    def test_improvement_flags_improve(self, tmp_path):
        ledger = tmp_path / "ledger.jsonl"
        _seed_ledger(ledger, "e2e_p99_ms",
                     [99.0, 100.0, 101.0, 100.0])
        entry = bench_history.record_run(
            {"e2e_p99_ms": 50.0}, source="test", ledger=str(ledger))
        assert entry["verdicts"]["e2e_p99_ms"]["verdict"] == "improve"

    def test_noise_within_one_mad_is_flat(self, tmp_path):
        ledger = tmp_path / "ledger.jsonl"
        values = [95.0, 100.0, 105.0, 98.0, 102.0, 100.0]
        _seed_ledger(ledger, "e2e_p99_ms", values)
        med = bench_history._median(values)
        mad = bench_history._mad(values, med)
        assert mad > 0
        entry = bench_history.record_run(
            {"e2e_p99_ms": med + mad},  # one MAD above the median
            source="test", ledger=str(ledger))
        assert entry["verdicts"]["e2e_p99_ms"]["verdict"] == "flat"

    def test_short_history_is_new_not_judged(self, tmp_path):
        ledger = tmp_path / "ledger.jsonl"
        _seed_ledger(ledger, "e2e_p99_ms", [100.0])
        entry = bench_history.record_run(
            {"e2e_p99_ms": 500.0}, source="test", ledger=str(ledger))
        assert entry["verdicts"]["e2e_p99_ms"]["verdict"] == "new"

    def test_failed_runs_excluded_from_baseline(self, tmp_path):
        ledger = tmp_path / "ledger.jsonl"
        _seed_ledger(ledger, "e2e_p99_ms", [100.0, 100.0, 100.0])
        # A crashed run with a garbage number must not widen the gate.
        bench_history.append_entry(str(ledger), {
            "ts": 0.0, "source": "crash", "ok": False,
            "metrics": {"e2e_p99_ms": 9999.0}, "meta": {},
        })
        entry = bench_history.record_run(
            {"e2e_p99_ms": 200.0}, source="test", ledger=str(ledger))
        assert entry["verdicts"]["e2e_p99_ms"]["verdict"] == "regress"

    def test_undirected_metrics_never_judged(self, tmp_path):
        ledger = tmp_path / "ledger.jsonl"
        _seed_ledger(ledger, "nodes", [100.0, 100.0, 100.0, 100.0])
        entry = bench_history.record_run(
            {"nodes": 5000.0}, source="test", ledger=str(ledger))
        assert "nodes" not in entry["verdicts"]
        assert entry["metrics"]["nodes"] == 5000.0  # recorded regardless


class TestDirectionInference:
    def test_known_directions(self):
        d = bench_history.direction
        assert d("eval_throughput") == 1
        assert d("live_pipeline_evals_per_sec_depth8") == 1
        assert d("live_pipeline_speedup") == 1
        assert d("e2e_p99_ms") == -1
        assert d("setup_s") == -1
        assert d("live_pipeline_latency_ms") == -1
        assert d("nodes") is None
        assert d("batch") is None


class TestNormalization:
    def test_wrapper_shape_with_parsed(self):
        raw = {"n": 3, "cmd": "python bench.py", "rc": 0, "tail": "...",
               "parsed": {"metric": "eval_throughput", "value": 969.5,
                          "p99_ms": 266.0, "platform": "tpu"}}
        entry = bench_history.normalize(raw, source="BENCH_r03.json")
        assert entry["ok"] is True
        assert entry["metrics"]["eval_throughput"] == 969.5
        assert entry["metrics"]["p99_ms"] == 266.0
        assert "platform" not in entry["metrics"]  # strings are not metrics

    def test_wrapper_shape_crashed_run(self):
        raw = {"n": 1, "cmd": "python bench.py", "rc": 1,
               "tail": "Traceback ...", "parsed": None}
        entry = bench_history.normalize(raw, source="BENCH_r01.json")
        assert entry["ok"] is False
        assert entry["metrics"] == {}

    def test_flat_dict_shape(self):
        entry = bench_history.normalize(
            {"live_pipeline_evals_per_sec_depth8": 101.4,
             "phase": "live_pipeline"})
        assert entry["ok"] is True
        assert entry["metrics"]["live_pipeline_evals_per_sec_depth8"] == 101.4
        assert entry["meta"]["phase"] == "live_pipeline"

    def test_nested_dicts_flatten_to_dotted_keys(self):
        entry = bench_history.normalize(
            {"e2e_host_only_phase_ms": {"plan.apply": {"p99_ms": 2.5}}})
        assert entry["metrics"][
            "e2e_host_only_phase_ms.plan.apply.p99_ms"] == 2.5


class TestRealFiles:
    def test_committed_bench_files_ingest(self, tmp_path):
        files = sorted(glob.glob(os.path.join(REPO, "BENCH_*.json")))
        files = [f for f in files if not f.endswith("BENCH_LEDGER.jsonl")]
        assert len(files) >= 5, files
        ledger = tmp_path / "ledger.jsonl"
        rc = bench_history.main(
            ["--ledger", str(ledger), "ingest"] + files)
        assert rc == 0
        entries = bench_history.read_ledger(str(ledger))
        assert len(entries) == len(files)
        ok = [e for e in entries if e["ok"]]
        assert len(ok) == len(files) - 1  # r01 crashed, rest parsed
        assert all(e["metrics"] for e in ok)

    def test_committed_ledger_parses(self):
        path = os.path.join(REPO, "BENCH_LEDGER.jsonl")
        entries = bench_history.read_ledger(path)
        assert len(entries) >= 6
        sources = {e["source"] for e in entries}
        assert "BENCH_r01.json" in sources
        assert "BENCH_live_pipeline.json" in sources

    def test_report_runs_on_committed_ledger(self, capsys):
        rc = bench_history.main(
            ["--ledger", os.path.join(REPO, "BENCH_LEDGER.jsonl"),
             "report", "--last", "10"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "runs shown" in out
