"""`nomad top` dashboard: the render path is a pure function of two
successive metric snapshots + the SLO/health reports, so the layout is
unit-testable without a server; `run_top --count N` is exercised
against a stub client."""

from __future__ import annotations

import io

from nomad_tpu.obs.top import CLEAR, render, run_top


def _metrics(evals=100, uptime=42):
    return {
        "uptime_s": uptime,
        "nomad.worker.evals_processed": evals,
        "nomad.plan.applied": evals,
        "nomad.broker.total_ready": 2,
        "nomad.broker.total_unacked": 1,
        "nomad.broker.total_pending": 0,
        "nomad.blocked_evals.total_blocked": 3,
        "nomad.plan.queue_depth": 1,
        "nomad.coalescer.inflight_depth": 2,
        "nomad.coalescer.pipeline_depth": 8,
        "nomad.coalescer.lane_fill_ratio": 0.75,
        "nomad.coalescer.stale_dispatches": 0,
        "nomad.phase.plan.apply": {
            "count": 50, "p50_ms": 0.5, "p99_ms": 2.0,
        },
        "nomad.phase.coalescer.device": {
            "count": 50, "p50_ms": 1.0, "p99_ms": 9.0,
        },
        "version": "x",  # non-numeric entries must not crash rendering
    }


def _slo():
    return {"slos": [{
        "name": "placement_latency_p99_ms", "objective": "nomad.eval.latency",
        "kind": "timer", "op": "<", "target": 5.0, "value": 3.91,
        "status": "ok", "burn_rate_fast": 0.4, "burn_rate_slow": 0.2,
        "windows_s": [60.0, 300.0], "budget": 0.05, "samples": [12, 40],
        "breached_since": None, "description": "",
    }]}


def _health():
    return {"status": "ok", "score": 97.3, "pressure": 0.027,
            "inputs": {}, "breached_slos": []}


class TestRender:
    def test_headline_and_queues(self):
        out = render(_metrics(), _slo(), _health(),
                     address="http://x:4646", interval=2.0)
        assert "health: ok" in out
        assert "score 97.3" in out
        assert "uptime 42s" in out
        assert "broker r/u/p: 2/1/0" in out
        assert "blocked: 3" in out
        assert "2/8 in flight" in out
        assert "lane fill 0.75" in out

    def test_shard_balance_row(self):
        cur = _metrics()
        cur.update({
            "nomad.matrix.shard_rows{shard=0}": 3,
            "nomad.matrix.shard_rows{shard=1}": 5,
            "nomad.matrix.shard_rows{shard=2}": 4,
            "nomad.matrix.shard_rows{shard=3}": 4,
            "nomad.topk.host_bytes_total": 2048,
        })
        out = render(cur, None, None)
        assert "rows 3/5/4/4" in out
        assert "skew 1.25" in out  # max 5 / mean 4
        assert "topk host bytes 2048" in out
        # A single-shard (or unsharded) matrix renders no shard row.
        assert "shards  :" not in render(_metrics(), None, None)

    def test_rates_are_deltas_between_snapshots(self):
        prev = _metrics(evals=100)
        cur = _metrics(evals=300)
        out = render(cur, _slo(), _health(), prev_metrics=prev,
                     interval=2.0)
        assert "evals/s :    100.0" in out  # (300-100)/2s
        # First frame has no prev: rates read 0, never garbage.
        first = render(cur, _slo(), _health(), interval=2.0)
        assert "evals/s :      0.0" in first

    def test_phase_table_sorted_by_where_time_goes(self):
        out = render(_metrics(), None, None)
        lines = out.splitlines()
        dev = next(i for i, l in enumerate(lines)
                   if "coalescer.device" in l)
        apply_ = next(i for i, l in enumerate(lines) if "plan.apply" in l)
        assert dev < apply_  # 50×9.0 > 50×2.0: device row first

    def test_slo_row_and_missing_reports(self):
        out = render(_metrics(), _slo(), _health())
        assert "placement_latency_p99_ms" in out
        assert "<5.0" in out
        # A follower (or a 501) yields slo/health None — still renders.
        bare = render(_metrics(), None, None)
        assert "health: ?" in bare

    def test_events_footer(self):
        out = render(_metrics(), _slo(), _health(),
                     events=["12:02:11 SLO SLOBreached placement_latency_p99_ms"])
        assert "events:" in out
        assert "SLOBreached" in out


class _StubClient:
    address = "http://stub:4646"
    token = ""

    def __init__(self):
        self.calls = 0

    def metrics(self):
        self.calls += 1
        return _metrics(evals=self.calls * 100)

    def slo(self):
        return _slo()

    def health(self):
        return _health()


class TestRunTop:
    def test_count_frames_then_exit(self):
        client = _StubClient()
        out = io.StringIO()
        rc = run_top(client, interval=0.01, count=3, clear=False, out=out)
        assert rc == 0
        assert client.calls == 3
        text = out.getvalue()
        assert CLEAR not in text  # --no-clear honored
        assert text.count("nomad top — http://stub:4646") == 3

    def test_endpoint_errors_degrade_gracefully(self):
        client = _StubClient()
        client.slo = lambda: (_ for _ in ()).throw(RuntimeError("501"))
        out = io.StringIO()
        rc = run_top(client, interval=0.01, count=1, clear=True, out=out)
        assert rc == 0
        assert "health: ok" in out.getvalue()
