"""Logmon — size-capped task log rotation (VERDICT r4 missing #7).

Reference: client/logmon/ + logging/rotator.go (N files x M bytes).
"""

from __future__ import annotations

import glob
import os
import time

import pytest

from helpers import _wait
from nomad_tpu import mock
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.client.logmon import LogRotator, rotate_once
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs.types import AllocClientStatus


class TestRotator:
    def test_rotate_once_shifts_and_truncates(self, tmp_path):
        p = str(tmp_path / "t.stdout")
        with open(p, "w") as fh:
            fh.write("AAA")
        rotate_once(p, max_files=3)
        assert os.path.getsize(p) == 0
        assert open(p + ".1").read() == "AAA"
        with open(p, "w") as fh:
            fh.write("BBB")
        rotate_once(p, max_files=3)
        assert open(p + ".1").read() == "BBB"
        assert open(p + ".2").read() == "AAA"
        # Third rotation drops the oldest (cap = 3 files incl. live).
        with open(p, "w") as fh:
            fh.write("CCC")
        rotate_once(p, max_files=3)
        assert open(p + ".1").read() == "CCC"
        assert open(p + ".2").read() == "BBB"
        assert not os.path.exists(p + ".3")

    def test_o_append_writer_survives_truncate(self, tmp_path):
        """The property copy-truncate depends on: an O_APPEND fd keeps
        writing at the new EOF after truncation."""
        p = str(tmp_path / "live")
        fd = open(p, "ab")
        fd.write(b"x" * 100)
        fd.flush()
        rotate_once(p, max_files=2)
        fd.write(b"after")
        fd.flush()
        assert open(p, "rb").read() == b"after"
        fd.close()

    def test_rotator_caps_growth(self, tmp_path):
        p = str(tmp_path / "chatty")
        rot = LogRotator([p], max_file_bytes=4096, max_files=3,
                         interval=0.05)
        rot.start()
        try:
            with open(p, "ab") as fh:
                for _ in range(200):
                    fh.write(b"y" * 512)
                    fh.flush()
                    time.sleep(0.002)
        finally:
            rot.stop()
        live = os.path.getsize(p)
        rotated = glob.glob(p + ".*")
        assert live <= 4096 + 512 * 40  # bounded, not 100KB
        assert len(rotated) <= 2
        total = live + sum(os.path.getsize(f) for f in rotated)
        assert total < 200 * 512  # history capped below what was written


class TestChattyTask:
    def test_raw_exec_logs_stay_under_cap(self, tmp_path):
        srv = Server(ServerConfig(
            num_workers=1, heartbeat_min_ttl=60, heartbeat_max_ttl=90
        ))
        srv.start()
        client = Client(srv, ClientConfig(data_dir=str(tmp_path / "c")))
        client.start()
        try:
            job = mock.job()
            job.type = "batch"
            tg = job.task_groups[0]
            tg.count = 1
            task = tg.tasks[0]
            task.driver = "raw_exec"
            task.resources.cpu = 20
            task.resources.memory_mb = 32
            tg.ephemeral_disk.size_mb = 10
            # ~2 MB of output against a 64 KB x 2-file cap.
            task.config = {
                "command": "/bin/sh",
                "args": ["-c",
                         "i=0; while [ $i -lt 2000 ]; do "
                         "printf '%01000d\\n' $i; i=$((i+1)); done; "
                         "sleep 1"],
            }
            task.logs = {"max_files": 2, "max_file_bytes": 65536}
            ev = srv.submit_job(job)
            srv.wait_for_eval(ev.id, timeout=90)
            assert _wait(lambda: any(
                a.terminal_status()
                for a in srv.store.allocs_by_job("default", job.id)
            ) and srv.store.allocs_by_job("default", job.id), timeout=60)
            alloc = srv.store.allocs_by_job("default", job.id)[0]
            stdout = os.path.join(
                str(tmp_path / "c"), alloc.id, task.name,
                f"{task.name}.stdout",
            )
            assert os.path.exists(stdout)
            files = [stdout] + glob.glob(stdout + ".*")
            total = sum(os.path.getsize(f) for f in files)
            # The task wrote ~2 MB; the cap holds it to the live file + one
            # rotated file (+ one burst window of slack).
            assert total < 500_000, (total, files)
            assert len(files) <= 2
        finally:
            client.shutdown()
            srv.shutdown()
