"""Tests for the device-resident NodeMatrix encoding."""

import numpy as np

from nomad_tpu.state import NodeMatrix, priority_bucket, stable_hash, numeric_value
from nomad_tpu.structs import (
    Allocation,
    DriverInfo,
    Job,
    Node,
    NodeReservedResources,
    NodeResources,
    Resources,
)


def make_node(**kw):
    defaults = dict(
        resources=NodeResources(cpu=4000, memory_mb=8192, disk_mb=100 * 1024),
        drivers={"mock": DriverInfo()},
    )
    defaults.update(kw)
    return Node(**defaults)


class TestEncoding:
    def test_stable_hash_nonzero(self):
        assert stable_hash("") != 0
        assert stable_hash("dc1") == stable_hash("dc1")
        assert stable_hash("dc1") != stable_hash("dc2")

    def test_numeric_value(self):
        assert numeric_value("42") == 42.0
        assert numeric_value("1.5") == 1.5
        assert np.isnan(numeric_value("1.2.3"))
        assert np.isnan(numeric_value("amd64"))

    def test_version_value(self):
        from nomad_tpu.state.matrix import version_value

        assert version_value("1.2.3") == 1e6 + 2e3 + 3
        assert version_value("2.0") == 2e6
        assert version_value("2") == 2e6
        assert version_value("v1.1.0") == 1e6 + 1e3
        assert np.isnan(version_value("amd64"))
        assert np.isnan(version_value("1.2.3.4"))

    def test_priority_bucket_bounds(self):
        assert priority_bucket(0) == 0
        assert priority_bucket(1) >= 0
        assert priority_bucket(100) == 15
        assert priority_bucket(50) < priority_bucket(90)


class TestNodeMatrix:
    def test_upsert_and_rows(self):
        m = NodeMatrix(capacity=16)
        n1, n2 = make_node(datacenter="dc1"), make_node(datacenter="dc2")
        r1, r2 = m.upsert_node(n1), m.upsert_node(n2)
        assert r1 != r2
        host = m.snapshot_host()
        assert host["eligible"][r1] and host["eligible"][r2]
        # totals = comparable resources
        assert host["totals"][r1][0] == 4000
        # datacenter is attr slot 0 (well-known registry order)
        assert host["attr_hash"][r1][0] == stable_hash("dc1")
        assert host["attr_hash"][r2][0] == stable_hash("dc2")

    def test_reserved_subtracted(self):
        m = NodeMatrix()
        node = make_node(reserved=NodeReservedResources(cpu=500, memory_mb=512))
        row = m.upsert_node(node)
        assert m.snapshot_host()["totals"][row][0] == 3500

    def test_alloc_accounting(self):
        m = NodeMatrix()
        node = make_node()
        row = m.upsert_node(node)
        job = Job(priority=50)
        alloc = Allocation(
            node_id=node.id, job=job, resources=Resources(cpu=1000, memory_mb=512)
        )
        m.add_alloc(alloc)
        host = m.snapshot_host()
        assert host["used"][row][0] == 1000
        assert host["prio_used"][row, priority_bucket(50), 0] == 1000
        m.remove_alloc(alloc)
        assert m.snapshot_host()["used"][row][0] == 0

    def test_class_dedup(self):
        m = NodeMatrix()
        a = make_node(node_class="web", attributes={"cpu.arch": "amd64"})
        b = make_node(node_class="web", attributes={"cpu.arch": "amd64"})
        c = make_node(node_class="db", attributes={"cpu.arch": "arm64"})
        ra, rb, rc = m.upsert_node(a), m.upsert_node(b), m.upsert_node(c)
        host = m.snapshot_host()
        # identical non-unique attrs → same computed class (node_class.go:28).
        assert host["class_id"][ra] == host["class_id"][rb]
        assert host["class_id"][ra] != host["class_id"][rc]

    def test_remove_and_reuse_row(self):
        m = NodeMatrix()
        n1 = make_node()
        r1 = m.upsert_node(n1)
        m.remove_node(n1.id)
        assert not m.snapshot_host()["eligible"][r1]
        n2 = make_node()
        r2 = m.upsert_node(n2)
        assert r2 == r1  # freed row reused

    def test_growth(self):
        m = NodeMatrix(capacity=16)
        nodes = [make_node() for _ in range(40)]
        for n in nodes:
            m.upsert_node(n)
        assert m.capacity >= 40
        assert m.snapshot_host()["eligible"][: m.n_rows].sum() == 40

    def test_device_sync_incremental(self):
        m = NodeMatrix()
        n1 = make_node()
        m.upsert_node(n1)
        d1 = m.sync()
        assert bool(d1.eligible[0])
        # Mutate and re-sync: scatter path.
        job = Job()
        m.add_alloc(
            Allocation(node_id=n1.id, job=job, resources=Resources(cpu=700, memory_mb=1))
        )
        d2 = m.sync()
        assert float(d2.used[0, 0]) == 700.0

    def test_gpu_devices(self):
        m = NodeMatrix()
        node = make_node()
        node.resources.devices = {"nvidia/gpu": ["a", "b"]}
        row = m.upsert_node(node)
        slot = m.devices.lookup("nvidia/gpu")
        assert m.snapshot_host()["dev_total"][row, slot] == 2
