"""Heartbeat timer wheel + client GC (VERDICT r3 item 10).

The old heartbeat manager armed one threading.Timer per node (10K nodes =
10K threads; the bench had to disarm it).  The wheel serves any node count
from ONE thread.  Client GC evicts terminal alloc dirs under a count
budget (client/gc.go analog).
"""

from __future__ import annotations

import os
import threading
import time

import pytest

from helpers import _client, _small, _wait
from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.heartbeat import HeartbeatManager
from nomad_tpu.structs.types import AllocClientStatus


class TestHeartbeatWheel:
    def test_single_thread_many_nodes(self):
        expired = []
        hb = HeartbeatManager(expired.append, min_ttl=0.15, max_ttl=0.25)
        hb.set_enabled(True)
        try:
            before = threading.active_count()
            for i in range(500):
                hb.reset_heartbeat(f"node-{i}")
            # One wheel thread, not one per node.
            assert threading.active_count() <= before + 1
            assert hb.tracked() == 500
            assert _wait(lambda: len(expired) == 500, timeout=10)
            assert hb.tracked() == 0
        finally:
            hb.set_enabled(False)

    def test_rearm_supersedes_old_deadline(self):
        expired = []
        hb = HeartbeatManager(expired.append, min_ttl=0.2, max_ttl=0.2)
        hb.set_enabled(True)
        try:
            hb.reset_heartbeat("n1")
            for _ in range(4):  # keep it alive past several old deadlines
                time.sleep(0.1)
                hb.reset_heartbeat("n1")
            assert expired == []
            assert _wait(lambda: expired == ["n1"], timeout=5)
        finally:
            hb.set_enabled(False)

    def test_clear_cancels(self):
        expired = []
        hb = HeartbeatManager(expired.append, min_ttl=0.15, max_ttl=0.15)
        hb.set_enabled(True)
        try:
            hb.reset_heartbeat("n1")
            hb.clear_heartbeat("n1")
            time.sleep(0.4)
            assert expired == []
        finally:
            hb.set_enabled(False)

    def test_server_detects_down_node(self, tmp_path):
        srv = Server(ServerConfig(
            num_workers=1, heartbeat_min_ttl=0.4, heartbeat_max_ttl=0.6
        ))
        srv.start()
        try:
            node = mock.node()
            srv.register_node(node)
            # No heartbeats arrive → the wheel marks the node down.
            assert _wait(lambda: (
                srv.store.node_by_id(node.id).status == "down"
            ), timeout=10)
        finally:
            srv.shutdown()


def test_client_gc_evicts_oldest_terminal_allocs(tmp_path):
    srv = Server(ServerConfig(
        num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90
    ))
    srv.start()
    c = _client(srv, tmp_path, "c1", max_terminal_allocs=3)
    try:
        jobs = []
        for i in range(6):
            job = _small(mock.job())
            tg = job.task_groups[0]
            tg.count = 1
            tg.tasks[0].config = {"run_for": 0.05}  # finish immediately
            jobs.append(job)
            ev = srv.submit_job(job)
            srv.wait_for_eval(ev.id, timeout=60)
        # All six complete...
        assert _wait(lambda: all(
            a.client_status == AllocClientStatus.COMPLETE.value
            for j in jobs
            for a in srv.store.allocs_by_job(j.namespace, j.id)
        ), timeout=60)
        # ...and the client holds at most the budget of terminal runners,
        # with the evicted alloc dirs removed from disk.
        def gc_done():
            with c._lock:
                terminal = [a for a in c.allocs.values() if a.terminal]
            return len(terminal) <= 3
        assert _wait(gc_done, timeout=30)
        with c._lock:
            kept = {aid for aid, ar in c.allocs.items()}
        data_dirs = {
            d for d in os.listdir(c.data_dir)
            if os.path.isdir(os.path.join(c.data_dir, d))
        }
        evicted = {
            a.id for j in jobs
            for a in srv.store.allocs_by_job(j.namespace, j.id)
        } - kept
        assert evicted, "nothing was evicted"
        assert not (evicted & data_dirs), "evicted alloc dirs still on disk"
    finally:
        c.shutdown()
        srv.shutdown()


class TestHeartbeatStop:
    def test_stop_after_client_disconnect(self, tmp_path):
        """client/heartbeatstop.go: a partitioned client kills groups
        that opted into stop_after_client_disconnect; others keep
        running."""
        from nomad_tpu import mock
        from nomad_tpu.client import Client, ClientConfig
        from nomad_tpu.server import Server, ServerConfig
        from nomad_tpu.structs.types import AllocClientStatus

        # Server-side expiry must stay OUT of the picture (wide TTLs):
        # this tests the CLIENT's disconnect policy; a 1s TTL lets a
        # loaded machine mark the node down before the partition starts.
        srv = Server(ServerConfig(
            num_workers=2, heartbeat_min_ttl=60.0, heartbeat_max_ttl=90.0
        ))
        srv.start()
        client = Client(srv, ClientConfig(data_dir=str(tmp_path / "c")))
        client.start()
        try:
            def submit(stop_after):
                job = mock.job()
                tg = job.task_groups[0]
                tg.count = 1
                tg.stop_after_client_disconnect = stop_after
                for t in tg.tasks:
                    t.resources.cpu = 20
                    t.resources.memory_mb = 32
                tg.ephemeral_disk.size_mb = 10
                ev = srv.submit_job(job)
                srv.wait_for_eval(ev.id, timeout=90)
                return job

            stopping = submit(1.5)
            surviving = submit(None)
            for job in (stopping, surviving):
                assert _wait(lambda j=job: any(
                    a.client_status == AllocClientStatus.RUNNING.value
                    for a in srv.store.allocs_by_job("default", j.id)
                ), timeout=60)

            # Partition: heartbeats start failing.
            class Unreachable:
                def __getattr__(self, name):
                    def boom(*a, **kw):
                        raise ConnectionError("partitioned")
                    return boom

            client.server = Unreachable()

            stop_ar = next(
                ar for ar in client.allocs.values()
                if ar.alloc.job_id == stopping.id
            )
            live_ar = next(
                ar for ar in client.allocs.values()
                if ar.alloc.job_id == surviving.id
            )
            assert _wait(lambda: stop_ar.terminal, timeout=30)
            assert not live_ar.terminal
        finally:
            client.server = srv
            client.shutdown()
            srv.shutdown()

    def test_reconnect_restores_ready(self, tmp_path):
        """A node marked DOWN by server-side TTL expiry must return to
        READY service after the partition heals: the server demotes
        DOWN -> INIT on the first heartbeat back (node_endpoint.go:476)
        and the CLIENT pushes READY on reconnect."""
        import time as _time

        from nomad_tpu.client import Client, ClientConfig
        from nomad_tpu.server import Server, ServerConfig
        from nomad_tpu.structs.types import NodeStatus

        srv = Server(ServerConfig(
            num_workers=1, heartbeat_min_ttl=60, heartbeat_max_ttl=90
        ))
        srv.start()
        client = Client(srv, ClientConfig(data_dir=str(tmp_path / "c")))
        client.start()
        try:
            node_id = client.node.id

            class Unreachable:
                def __getattr__(self, name):
                    def boom(*a, **kw):
                        raise ConnectionError("partitioned")
                    return boom

            real = client.server
            client.server = Unreachable()
            # Server-side expiry fires (simulate the wheel's verdict).
            srv._on_heartbeat_expired(node_id)
            assert srv.store.node_by_id(
                node_id
            ).status == NodeStatus.DOWN.value
            # Wait until the client has noticed the partition.
            assert _wait(
                lambda: client._disconnected_since is not None, timeout=30
            )
            # Heal: the client's fast reconnect probe restores READY.
            client.server = real
            assert _wait(lambda: srv.store.node_by_id(
                node_id
            ).status == NodeStatus.READY.value, timeout=30)
        finally:
            client.shutdown()
            srv.shutdown()
