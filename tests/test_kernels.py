"""Golden tests: JAX scheduling kernels vs. the scalar oracle.

Tier-1 strategy from SURVEY.md §4: the vectorized kernels are parity-tested
against the scalar reference implementation (nomad_tpu.structs.funcs, which
mirrors nomad/structs/funcs.go and scheduler/rank.go semantics).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from nomad_tpu.ops import RequestEncoder, place_task_group, verify_plan_fit
from nomad_tpu.ops.kernels import NEG_INF, score_nodes
from nomad_tpu.state import NodeMatrix
from nomad_tpu.structs import (
    Affinity,
    Allocation,
    Constraint,
    DriverInfo,
    Job,
    Node,
    NodeResources,
    Resources,
    Spread,
    SpreadTarget,
    Task,
    TaskGroup,
    score_fit_binpack,
    score_fit_spread,
)


def make_node(cpu=4000, mem=8192, dc="dc1", node_class="", attrs=None, **kw):
    return Node(
        datacenter=dc,
        node_class=node_class,
        attributes=attrs or {},
        resources=NodeResources(cpu=cpu, memory_mb=mem, disk_mb=100 * 1024),
        drivers={"mock": DriverInfo()},
        **kw,
    )


def make_job(cpu=500, mem=256, count=1, constraints=None, affinities=None,
             spreads=None, **kw):
    tg = TaskGroup(
        name="web",
        count=count,
        tasks=[Task(resources=Resources(cpu=cpu, memory_mb=mem))],
        constraints=constraints or [],
        affinities=affinities or [],
        spreads=spreads or [],
    )
    return Job(task_groups=[tg], **kw)


def setup(nodes):
    m = NodeMatrix(capacity=max(16, len(nodes)))
    for n in nodes:
        m.upsert_node(n)
    return m


def run_place(m, job, count=1, algorithm="binpack", penalty_rows=(),
              preemption=False):
    enc = RequestEncoder(m)
    tg = job.task_groups[0]
    compiled = enc.compile(job, tg, algorithm=algorithm,
                           preemption_enabled=preemption)
    arrays = m.sync()
    n = arrays.used.shape[0]
    penalty = np.zeros((n,), bool)
    for r in penalty_rows:
        penalty[r] = True
    from nomad_tpu.ops.encode import MAX_SPREADS, MAX_SPREAD_VALUES

    spread_counts = jnp.zeros((MAX_SPREADS, MAX_SPREAD_VALUES), jnp.float32)
    tg_count = jnp.zeros((n,), jnp.int32)
    return place_task_group(
        arrays,
        compiled.request,
        arrays.used,
        tg_count,
        spread_counts,
        jnp.asarray(penalty),
        None,
        None,
        count,
    )


class TestBinpackSelection:
    def test_picks_most_packed_node(self):
        # Binpack prefers the node whose post-placement utilization is higher.
        busy, idle = make_node(), make_node()
        m = setup([busy, idle])
        job0 = Job()
        m.add_alloc(Allocation(node_id=busy.id, job=job0,
                               resources=Resources(cpu=2000, memory_mb=4096)))
        res = run_place(m, make_job())
        assert int(res.rows[0]) == m.row_of[busy.id]

    def test_spread_algorithm_picks_empty_node(self):
        busy, idle = make_node(), make_node()
        m = setup([busy, idle])
        m.add_alloc(Allocation(node_id=busy.id, job=Job(),
                               resources=Resources(cpu=2000, memory_mb=4096)))
        res = run_place(m, make_job(), algorithm="spread")
        assert int(res.rows[0]) == m.row_of[idle.id]

    def test_binpack_score_matches_oracle(self):
        node = make_node(cpu=4000, mem=8192)
        m = setup([node])
        m.add_alloc(Allocation(node_id=node.id, job=Job(),
                               resources=Resources(cpu=1000, memory_mb=2048)))
        res = run_place(m, make_job(cpu=500, mem=256))
        util = Resources(cpu=1500, memory_mb=2304)
        expected = score_fit_binpack(node, util) / 18.0
        assert np.isclose(float(res.binpack[0]), expected, atol=1e-5)

    def test_resource_exhaustion(self):
        node = make_node(cpu=1000, mem=1024)
        m = setup([node])
        res = run_place(m, make_job(cpu=2000, mem=100))
        assert int(res.rows[0]) == -1
        assert int(res.nodes_exhausted[0]) == 1

    def test_sequential_placements_account_usage(self):
        # Two placements of 600 CPU on a 1000-CPU node: second must go elsewhere.
        small, big = make_node(cpu=1000, mem=8192), make_node(cpu=4000, mem=8192)
        m = setup([small, big])
        res = run_place(m, make_job(cpu=600, mem=100, count=2), count=2)
        rows = {int(res.rows[0]), int(res.rows[1])}
        assert rows == {m.row_of[small.id], m.row_of[big.id]} or rows == {m.row_of[big.id]}
        # used_after reflects both placements
        assert float(res.used_after.sum()) >= 1200


class TestFeasibility:
    def test_datacenter_filter(self):
        n1, n2 = make_node(dc="dc1"), make_node(dc="dc2")
        m = setup([n1, n2])
        job = make_job()
        job.datacenters = ["dc2"]
        res = run_place(m, job)
        assert int(res.rows[0]) == m.row_of[n2.id]

    def test_constraint_eq(self):
        n1 = make_node(attrs={"kernel.name": "linux"})
        n2 = make_node(attrs={"kernel.name": "darwin"})
        m = setup([n1, n2])
        job = make_job(constraints=[
            Constraint(l_target="${attr.kernel.name}", operand="=", r_target="linux")
        ])
        res = run_place(m, job)
        assert int(res.rows[0]) == m.row_of[n1.id]

    def test_constraint_neq_passes_missing_attr(self):
        # "!=" passes when the attribute is absent (feasible.go:797).
        n1 = make_node(attrs={"foo.bar": "x"})
        n2 = make_node()
        m = setup([n1, n2])
        job = make_job(constraints=[
            Constraint(l_target="${attr.foo.bar}", operand="!=", r_target="x")
        ])
        res = run_place(m, job)
        assert int(res.rows[0]) == m.row_of[n2.id]

    def test_numeric_comparison(self):
        n1 = make_node(attrs={"cpu.numcores": "4"})
        n2 = make_node(attrs={"cpu.numcores": "16"})
        m = setup([n1, n2])
        job = make_job(constraints=[
            Constraint(l_target="${attr.cpu.numcores}", operand=">=", r_target="8")
        ])
        res = run_place(m, job)
        assert int(res.rows[0]) == m.row_of[n2.id]

    def test_version_constraint(self):
        n1 = make_node(attrs={"os.version": "1.2.3"})
        n2 = make_node(attrs={"os.version": "2.0.0"})
        m = setup([n1, n2])
        job = make_job(constraints=[
            Constraint(l_target="${attr.os.version}", operand="version",
                       r_target=">= 2.0")
        ])
        res = run_place(m, job)
        assert int(res.rows[0]) == m.row_of[n2.id]

    def test_driver_filter(self):
        n1 = make_node()
        n2 = make_node()
        n2.drivers = {"docker": DriverInfo()}  # no mock driver
        m = setup([n1, n2])
        res = run_place(m, make_job())  # mock driver task
        assert int(res.rows[0]) == m.row_of[n1.id]

    def test_ineligible_node_filtered(self):
        n1, n2 = make_node(), make_node()
        n2.drain = True
        m = setup([n1, n2])
        res = run_place(m, make_job())
        assert int(res.rows[0]) == m.row_of[n1.id]

    def test_no_feasible_nodes(self):
        m = setup([make_node(dc="dc9")])
        res = run_place(m, make_job())  # wants dc1
        assert int(res.rows[0]) == -1
        assert int(res.nodes_filtered[0]) == 1

    def test_device_constraint(self):
        gpu_node = make_node()
        gpu_node.resources.devices = {"gpu": ["g0", "g1"]}
        plain = make_node()
        m = setup([gpu_node, plain])
        from nomad_tpu.structs import RequestedDevice

        job = make_job()
        job.task_groups[0].tasks[0].resources.devices = [
            RequestedDevice(name="gpu", count=1)
        ]
        res = run_place(m, job)
        assert int(res.rows[0]) == m.row_of[gpu_node.id]


class TestScoring:
    def test_anti_affinity_spreads_same_job(self):
        # With equal binpack, a node already hosting this TG is penalized
        # (rank.go:601: -(collisions+1)/desired_count appended when >0).
        a, b = make_node(), make_node()
        m = setup([a, b])
        res = run_place(m, make_job(count=2), count=2)
        assert {int(res.rows[0]), int(res.rows[1])} == {0, 1}

    def test_reschedule_penalty_avoids_prev_node(self):
        a, b = make_node(), make_node()
        m = setup([a, b])
        res = run_place(m, make_job(), penalty_rows=[m.row_of[a.id]])
        assert int(res.rows[0]) == m.row_of[b.id]

    def test_affinity_attracts(self):
        n1 = make_node(attrs={"rack": "r1"})
        n2 = make_node(attrs={"rack": "r2"})
        m = setup([n1, n2])
        job = make_job(affinities=[
            Affinity(l_target="${attr.rack}", operand="=", r_target="r2", weight=100)
        ])
        res = run_place(m, job)
        assert int(res.rows[0]) == m.row_of[n2.id]

    def test_negative_affinity_repels(self):
        n1 = make_node(attrs={"rack": "r1"})
        n2 = make_node(attrs={"rack": "r2"})
        m = setup([n1, n2])
        job = make_job(affinities=[
            Affinity(l_target="${attr.rack}", operand="=", r_target="r2", weight=-100)
        ])
        res = run_place(m, job)
        assert int(res.rows[0]) == m.row_of[n1.id]

    def test_even_spread(self):
        # Even spread over node.datacenter: 4 placements over 2 DCs → 2+2.
        nodes = [make_node(dc="dc1"), make_node(dc="dc1"),
                 make_node(dc="dc2"), make_node(dc="dc2")]
        m = setup(nodes)
        job = make_job(count=4, spreads=[Spread(attribute="${node.datacenter}")])
        job.datacenters = ["dc1", "dc2"]
        res = run_place(m, job, count=4)
        dcs = [nodes[int(r)].datacenter for r in res.rows]
        assert sorted(dcs) == ["dc1", "dc1", "dc2", "dc2"]

    def test_targeted_spread(self):
        # 70/30 split over 10 placements lands ~7/3.
        nodes = [make_node(dc="dc1", cpu=100000, mem=100000),
                 make_node(dc="dc2", cpu=100000, mem=100000)]
        m = setup(nodes)
        job = make_job(
            cpu=10, mem=10, count=10,
            spreads=[Spread(attribute="${node.datacenter}", weight=100,
                            targets=[SpreadTarget(value="dc1", percent=70),
                                     SpreadTarget(value="dc2", percent=30)])],
        )
        job.datacenters = ["dc1", "dc2"]
        res = run_place(m, job, count=10)
        dcs = [nodes[int(r)].datacenter for r in res.rows]
        # Job anti-affinity (always active in the generic stack) interleaves
        # with targeted spread, so the split lands near — not exactly on —
        # 7/3 (hand-tracing the reference formulas gives 6/4..7/3).
        assert dcs.count("dc1") in (6, 7)
        assert dcs.count("dc2") == 10 - dcs.count("dc1")


class TestPreemption:
    def test_preemption_enables_placement(self):
        # Node full of low-priority work; high-priority job preempts.
        node = make_node(cpu=1000, mem=1024)
        m = setup([node])
        low = Job(priority=10)
        m.add_alloc(Allocation(node_id=node.id, job=low,
                               resources=Resources(cpu=900, memory_mb=900)))
        job = make_job(cpu=500, mem=500)
        job.priority = 70
        res = run_place(m, job, preemption=False)
        assert int(res.rows[0]) == -1
        res = run_place(m, job, preemption=True)
        assert int(res.rows[0]) == m.row_of[node.id]
        assert bool(res.preempted[0])

    def test_no_preemption_of_high_priority(self):
        # Victims must be > 10 priority points below (preemption.go:663).
        node = make_node(cpu=1000, mem=1024)
        m = setup([node])
        m.add_alloc(Allocation(node_id=node.id, job=Job(priority=65),
                               resources=Resources(cpu=900, memory_mb=900)))
        job = make_job(cpu=500, mem=500)
        job.priority = 70
        res = run_place(m, job, preemption=True)
        assert int(res.rows[0]) == -1


class TestVerifyPlanFit:
    def test_verify(self):
        n1 = make_node(cpu=1000, mem=1024)
        n2 = make_node(cpu=4000, mem=8192)
        m = setup([n1, n2])
        m.add_alloc(Allocation(node_id=n1.id, job=Job(),
                               resources=Resources(cpu=800, memory_mb=100)))
        arrays = m.sync()
        rows = jnp.asarray([m.row_of[n1.id], m.row_of[n2.id], -1], jnp.int32)
        deltas = jnp.asarray(
            [[500.0, 10.0, 0.0], [500.0, 10.0, 0.0], [0, 0, 0]], jnp.float32
        )
        elig = jnp.asarray([True, True, True])
        ok = verify_plan_fit(arrays, rows, deltas, elig)
        assert not bool(ok[0])  # 800+500 > 1000
        assert bool(ok[1])
        assert bool(ok[2])  # padding passes

    def test_host_twin_matches_kernel(self):
        """The plan applier's host fast path (plan_apply._evaluate) must be
        bit-identical to verify_plan_fit over the same aggregates."""
        rng = np.random.default_rng(3)
        nodes = [
            make_node(cpu=int(c), mem=int(mm))
            for c, mm in rng.integers(500, 8000, (12, 2))
        ]
        m = setup(nodes)
        for n in nodes[:6]:
            m.add_alloc(Allocation(node_id=n.id, job=Job(), resources=(
                Resources(cpu=int(rng.integers(100, 2000)),
                          memory_mb=int(rng.integers(100, 2000))))))
        m.snapshot_host()["eligible"][3] = False
        m._dirty.add(3)
        arrays = m.sync()
        host = m.snapshot_host()

        k = 12
        rows = np.arange(k, dtype=np.int32)
        deltas = rng.uniform(0, 4000, (k, 3)).astype(np.float32)
        elig_required = rng.random(k) < 0.5

        kernel = np.asarray(verify_plan_fit(
            arrays, jnp.asarray(rows), jnp.asarray(deltas),
            jnp.asarray(elig_required),
        ))
        used = host["used"][rows] + deltas
        fits = np.all(used <= host["totals"][rows], axis=1)
        host_v = fits & (~elig_required | host["eligible"][rows])
        assert (kernel == host_v).all()


class TestPlaceBatch:
    def test_matches_solo_scan(self):
        """place_batch (the coalescer kernel) must equal per-request
        place_task_group runs, including sparse delta application."""
        from nomad_tpu.ops.encode import MAX_SPREADS, MAX_SPREAD_VALUES
        from nomad_tpu.ops.kernels import place_batch

        nodes = [make_node(cpu=2000 + 500 * i, mem=4096) for i in range(6)]
        m = setup(nodes)
        jobs = [make_job(cpu=300 + 100 * i, mem=256) for i in range(3)]
        enc = RequestEncoder(m)
        compiled = [enc.compile(j, j.task_groups[0]) for j in jobs]
        arrays = m.sync()
        n = arrays.used.shape[0]

        scan_len = 4
        drows = np.full((3, 8), -1, np.int32)
        dvals = np.zeros((3, 8, 3), np.float32)
        # Request 1 carries an in-flight delta on row 5.
        drows[1, 0] = 5
        dvals[1, 0] = [1500.0, 0.0, 0.0]

        import jax

        reqs = jax.tree_util.tree_map(
            lambda *xs: np.stack(xs), *[c.request for c in compiled]
        )
        zeros_tg = np.zeros((3, n), np.int32)
        zeros_sc = np.zeros((3, MAX_SPREADS, MAX_SPREAD_VALUES), np.float32)
        zeros_pen = np.zeros((3, n), bool)
        ones_ce = np.ones((3, 2), bool)
        ones_hm = np.ones((3, n), bool)
        packed = np.asarray(place_batch(
            arrays, arrays.used, drows, dvals, zeros_tg, zeros_sc,
            zeros_pen, reqs, ones_ce, ones_hm, n_placements=scan_len,
        ))

        for i, c in enumerate(compiled):
            used0 = arrays.used
            if i == 1:
                used0 = used0.at[5].add(jnp.asarray([1500.0, 0.0, 0.0]))
            solo = place_task_group(
                arrays, c.request, used0, jnp.zeros((n,), jnp.int32),
                jnp.zeros((MAX_SPREADS, MAX_SPREAD_VALUES), jnp.float32),
                jnp.zeros((n,), bool), jnp.ones((2,), bool),
                jnp.ones((n,), bool), scan_len,
            )
            assert (packed[i, :, 0].astype(np.int32)
                    == np.asarray(solo.rows)).all()
            np.testing.assert_allclose(
                packed[i, :, 1], np.asarray(solo.scores), rtol=1e-5
            )


class TestEncodingEscapes:
    def test_version_two_component_attr(self):
        # Node attr "2.0" must satisfy "version >= 1.5" (version packing is
        # applied on both sides; plain-numeric and version columns are split).
        n1 = make_node(attrs={"os.version": "2.0"})
        m = setup([n1])
        job = make_job(constraints=[
            Constraint(l_target="${attr.os.version}", operand="version",
                       r_target=">= 1.5")
        ])
        res = run_place(m, job)
        assert int(res.rows[0]) == m.row_of[n1.id]

    def test_device_registry_overflow_escapes(self):
        m = setup([make_node()])
        for i in range(m.devices.slots):
            m.devices.register(f"dev{i}")
        from nomad_tpu.structs import RequestedDevice
        from nomad_tpu.ops import RequestEncoder

        job = make_job()
        job.task_groups[0].tasks[0].resources.devices = [
            RequestedDevice(name="unregistered/tpu", count=1)
        ]
        enc = RequestEncoder(m)
        compiled = enc.compile(job, job.task_groups[0])
        assert compiled.escaped_devices == [("unregistered/tpu", 1)]

    def test_datacenter_overflow_escapes(self):
        n = make_node(dc="dc9")
        m = setup([n])
        from nomad_tpu.ops import RequestEncoder

        job = make_job()
        job.datacenters = [f"dc{i}" for i in range(12)]  # > MAX_DATACENTERS
        enc = RequestEncoder(m)
        compiled = enc.compile(job, job.task_groups[0])
        assert compiled.dc_escaped
        # Kernel skips the dc check; host filter takes over.
        res = run_place(m, job)
        assert int(res.rows[0]) == m.row_of[n.id]
