"""alloc exec (VERDICT r4 missing #6): run a command in a task's context
over the chunked-HTTP client surface, with server→node-agent forwarding.

Reference: plugins/drivers/execstreaming.go, nomad/client_rpc.go (the
reverse-session forwarding), command/alloc_exec.go.
"""

from __future__ import annotations

import socket
import time

import pytest

from helpers import _wait
from nomad_tpu import mock
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.api.client import APIClient, APIError
from nomad_tpu.client import ClientConfig
from nomad_tpu.server import ServerConfig
from nomad_tpu.structs.types import AllocClientStatus


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.fixture
def two_agents(tmp_path):
    """A server-only agent + a client-only agent over the real HTTP wire
    (the tier-2 two-OS-process pattern, in-process here)."""
    sp = _free_port()
    server_agent = Agent(AgentConfig(
        name="srv",
        server_enabled=True,
        client_enabled=False,
        http_host="127.0.0.1",
        http_port=sp,
        server_config=ServerConfig(
            num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90
        ),
    ))
    server_agent.start()
    client_agent = Agent(AgentConfig(
        name="cli",
        server_enabled=False,
        client_enabled=True,
        http_host="127.0.0.1",
        http_port=_free_port(),
        server_addr=f"http://127.0.0.1:{sp}",
        client_config=ClientConfig(data_dir=str(tmp_path / "client")),
    ))
    client_agent.start()
    yield server_agent, client_agent
    client_agent.shutdown()
    server_agent.shutdown()


def _run_job(server):
    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 1
    task = tg.tasks[0]
    task.driver = "raw_exec"
    task.resources.cpu = 20
    task.resources.memory_mb = 32
    tg.ephemeral_disk.size_mb = 10
    task.config = {"command": "/bin/sleep", "args": ["30"]}
    task.env = {"GREETING": "bonjour"}
    ev = server.submit_job(job)
    server.wait_for_eval(ev.id, timeout=90)
    return job


class TestAllocExec:
    def test_exec_through_server_forwarding(self, two_agents):
        """The full path: API → SERVER agent → forward to the node agent →
        subprocess in the task dir → NDJSON frames back."""
        server_agent, client_agent = two_agents
        srv = server_agent.server
        job = _run_job(srv)
        assert _wait(lambda: any(
            a.client_status == AllocClientStatus.RUNNING.value
            for a in srv.store.allocs_by_job("default", job.id)
        ), timeout=60)
        alloc = srv.store.allocs_by_job("default", job.id)[0]

        api = APIClient(server_agent.rpc_addr)  # hits the SERVER agent
        code, out, err = api.alloc_exec(
            alloc.id, "", ["/bin/sh", "-c", "pwd; echo $GREETING"],
        )
        assert code == 0, (out, err)
        lines = out.decode().strip().splitlines()
        assert lines[0].endswith(f"/{alloc.id}/web")  # task dir cwd
        assert lines[1] == "bonjour"  # task env applied

    def test_exec_stdin_and_exit_code(self, two_agents):
        server_agent, client_agent = two_agents
        srv = server_agent.server
        job = _run_job(srv)
        assert _wait(lambda: any(
            a.client_status == AllocClientStatus.RUNNING.value
            for a in srv.store.allocs_by_job("default", job.id)
        ), timeout=60)
        alloc = srv.store.allocs_by_job("default", job.id)[0]
        api = APIClient(client_agent.rpc_addr)  # node agent directly

        code, out, _ = api.alloc_exec(
            alloc.id, "web", ["/bin/cat"], stdin=b"piped-input",
        )
        assert code == 0
        assert out == b"piped-input"

        code, _, err = api.alloc_exec(
            alloc.id, "web", ["/bin/sh", "-c", "echo boom >&2; exit 3"],
        )
        assert code == 3
        assert b"boom" in err

    def test_exec_unknown_alloc_and_task(self, two_agents):
        server_agent, client_agent = two_agents
        api = APIClient(server_agent.rpc_addr)
        with pytest.raises(APIError) as exc:
            api.alloc_exec("nope", "web", ["/bin/true"])
        assert exc.value.code == 404

    def test_cli_alloc_exec(self, two_agents, capsys):
        from nomad_tpu.cli import main

        server_agent, client_agent = two_agents
        srv = server_agent.server
        job = _run_job(srv)
        assert _wait(lambda: any(
            a.client_status == AllocClientStatus.RUNNING.value
            for a in srv.store.allocs_by_job("default", job.id)
        ), timeout=60)
        alloc = srv.store.allocs_by_job("default", job.id)[0]
        rc = main([
            "--address", server_agent.rpc_addr,
            "alloc", "exec", alloc.id, "--",
            "/bin/echo", "hello from exec",
        ])
        out = capsys.readouterr().out
        assert rc == 0
        assert "hello from exec" in out
