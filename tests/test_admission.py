"""Job admission pipeline (nomad/job_endpoint_hooks.go): mutate +
validate at register time; /v1/validate/job dry run; `job validate`
CLI."""

from __future__ import annotations

import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.admission import admit
from nomad_tpu.structs.types import (
    Constraint,
    ScalingPolicy,
    Task,
    TaskGroup,
    VolumeMount,
)


@pytest.fixture
def server():
    s = Server(ServerConfig(
        num_workers=0, heartbeat_min_ttl=60, heartbeat_max_ttl=90
    ))
    s.start()
    yield s
    s.shutdown()


class TestAdmit:
    def test_canonicalizes(self):
        job = mock.job()
        job.name = ""
        job.datacenters = []
        admit(job)
        assert job.name == job.id
        assert job.datacenters == ["dc1"]

    def test_collects_all_errors(self):
        job = mock.job()
        job.priority = 500
        job.type = "weird"
        tg = job.task_groups[0]
        tg.count = -1
        tg.tasks.append(Task(name=tg.tasks[0].name))  # duplicate name
        with pytest.raises(ValueError) as exc:
            admit(job)
        msg = str(exc.value)
        # Job-level operand errors appear once, not once per group.
        job2 = mock.job()
        job2.task_groups.append(mock.job().task_groups[0])
        job2.task_groups[1].name = "other"
        job2.constraints = [Constraint(
            l_target="${attr.x}", r_target="y", operand="~="
        )]
        with pytest.raises(ValueError) as exc2:
            admit(job2)
        assert str(exc2.value).count("unknown constraint operand") == 1
        # Task-level operands are validated too.
        job3 = mock.job()
        job3.task_groups[0].tasks[0].constraints = [Constraint(
            l_target="${attr.x}", r_target="y", operand="!!"
        )]
        with pytest.raises(ValueError):
            admit(job3)
        assert "priority" in msg
        assert "unknown job type" in msg
        assert "negative count" in msg
        assert "duplicate task" in msg

    def test_rejects_bad_operand_and_dangling_mount(self):
        job = mock.job()
        tg = job.task_groups[0]
        tg.constraints = [Constraint(
            l_target="${attr.x}", r_target="y", operand="~="
        )]
        tg.tasks[0].volume_mounts = [VolumeMount(volume="ghost")]
        with pytest.raises(ValueError) as exc:
            admit(job)
        assert "operand" in str(exc.value)
        assert "undeclared volume" in str(exc.value)

    def test_rejects_scaling_min_over_max(self):
        job = mock.job()
        job.task_groups[0].scaling = ScalingPolicy(min=5, max=2)
        with pytest.raises(ValueError):
            admit(job)

    def test_server_rejects_before_journal(self, server):
        job = mock.job()
        job.priority = 0
        with pytest.raises(ValueError):
            server.submit_job(job)
        assert server.store.job_by_id(job.namespace, job.id) is None


class TestValidateEndpoint:
    def test_http_validate_dry_run(self, tmp_path):
        from nomad_tpu.api import Agent, AgentConfig
        from nomad_tpu.api.client import APIClient
        from nomad_tpu.client import ClientConfig
        from nomad_tpu.jobspec import job_to_api

        a = Agent(AgentConfig(
            server_config=ServerConfig(
                num_workers=0, heartbeat_min_ttl=60, heartbeat_max_ttl=90
            ),
            client_config=ClientConfig(data_dir=str(tmp_path / "c")),
        ))
        a.start()
        try:
            api = APIClient(a.rpc_addr)
            good = mock.job()
            out = api.validate_job(job_to_api(good))
            assert out["Valid"] is True

            bad = mock.job()
            bad.priority = -3
            out = api.validate_job(job_to_api(bad))
            assert out["Valid"] is False
            assert any("priority" in e for e in out["ValidationErrors"])
            # Type-malformed payloads are invalid input, not 500s.
            out = api.validate_job({"id": "x", "task_groups": [
                {"tasks": "oops"}
            ]})
            assert out["Valid"] is False
            assert any("malformed" in e for e in out["ValidationErrors"])
            # Nothing registered by the dry run.
            assert api.list_jobs() == []
        finally:
            a.shutdown()
