"""Task env interpolation + artifact/template hooks (VERDICT r3 missing
item 6: without these 'real workloads can't be expressed').

Reference: client/taskenv/ (NOMAD_* builder + ReplaceEnv),
task_runner_hooks.go:50-160 (artifact via go-getter, template render).
"""

from __future__ import annotations

import os

import pytest

from helpers import _wait
from nomad_tpu import mock
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.client.taskenv import build_task_env, interpolate, interpolation_map
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs.types import AllocClientStatus, Allocation, Task


@pytest.fixture
def server():
    s = Server(ServerConfig(
        num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90
    ))
    s.start()
    yield s
    s.shutdown()


class TestEnvBuilder:
    def test_identity_and_limits(self):
        job = mock.job()
        task = job.task_groups[0].tasks[0]
        alloc = Allocation(
            job_id=job.id, namespace=job.namespace, job=job,
            name=f"{job.id}.web[3]", task_group=job.task_groups[0].name,
            assigned_ports={"group": {"http": 23456}},
        )
        env = build_task_env(alloc, task, "/t", "/a")
        assert env["NOMAD_ALLOC_ID"] == alloc.id
        assert env["NOMAD_ALLOC_INDEX"] == "3"
        assert env["NOMAD_JOB_ID"] == job.id
        assert env["NOMAD_CPU_LIMIT"] == str(int(task.resources.cpu))
        assert env["NOMAD_PORT_http"] == "23456"
        assert env["NOMAD_ADDR_http"] == "127.0.0.1:23456"
        assert env["NOMAD_TASK_DIR"] == "/t"

    def test_interpolation(self):
        node = mock.node()
        node.attributes = dict(node.attributes)
        node.attributes["rack"] = "r7"
        table = interpolation_map({"NOMAD_JOB_ID": "j1"}, node)
        assert interpolate("${NOMAD_JOB_ID}-on-${attr.rack}", table) == (
            "j1-on-r7"
        )
        assert interpolate("${node.datacenter}", table) == node.datacenter
        # Unknown references stay intact (reference behavior).
        assert interpolate("${mystery.ref}", table) == "${mystery.ref}"
        assert interpolate(
            {"k": ["${NOMAD_JOB_ID}"]}, table
        ) == {"k": ["j1"]}


def test_task_sees_nomad_env_end_to_end(server, tmp_path):
    c = Client(server, ClientConfig(data_dir=str(tmp_path / "c")))
    c.start()
    try:
        job = mock.job()
        job.meta = {"owner": "team-a"}
        tg = job.task_groups[0]
        tg.count = 1
        tg.tasks = [Task(
            name="main", driver="raw_exec",
            config={
                "command": "/bin/sh",
                "args": [
                    "-c",
                    'echo "$NOMAD_ALLOC_ID|$NOMAD_META_owner|'
                    '${NOMAD_JOB_ID}" > "$NOMAD_TASK_DIR/out"; sleep 300',
                ],
            },
            env={"WHOAMI": "${NOMAD_TASK_NAME}@${node.datacenter}"},
        )]
        for t in tg.tasks:
            t.resources.cpu = 20
            t.resources.memory_mb = 32
        tg.ephemeral_disk.size_mb = 10
        server.submit_job(job)
        assert _wait(lambda: [
            a for a in server.store.allocs_by_job(job.namespace, job.id)
            if a.client_status == AllocClientStatus.RUNNING.value
        ], timeout=60)
        alloc = server.store.allocs_by_job(job.namespace, job.id)[0]
        out = os.path.join(c.data_dir, alloc.id, "main", "out")
        assert _wait(lambda: os.path.exists(out), timeout=15)
        alloc_id, owner, job_id = open(out).read().strip().split("|")
        assert alloc_id == alloc.id
        assert owner == "team-a"
        assert job_id == job.id
        # Task env values were interpolated too.
        tr = c.allocs[alloc.id].runners["main"]
        assert tr.task.env["WHOAMI"] == f"main@{c.node.datacenter}"
    finally:
        c.shutdown()


def test_artifact_and_template_hooks(server, tmp_path):
    src = tmp_path / "payload.txt"
    src.write_text("artifact-content")
    # file:// sources are sandboxed (ADVICE r4: a submit-job token must
    # not read arbitrary agent files) — allowlist the fixture dir.
    c = Client(server, ClientConfig(
        data_dir=str(tmp_path / "c"), artifact_root=str(tmp_path)
    ))
    c.start()
    try:
        job = mock.job()
        tg = job.task_groups[0]
        tg.count = 1
        tg.tasks = [Task(
            name="main", driver="raw_exec",
            config={"command": "/bin/sleep", "args": ["300"]},
            artifacts=[{"source": f"file://{src}", "destination": "local"}],
            templates=[{
                "data": "alloc=${NOMAD_ALLOC_ID}",
                "destination": "local/config.ini",
            }],
        )]
        for t in tg.tasks:
            t.resources.cpu = 20
            t.resources.memory_mb = 32
        tg.ephemeral_disk.size_mb = 10
        server.submit_job(job)
        assert _wait(lambda: [
            a for a in server.store.allocs_by_job(job.namespace, job.id)
            if a.client_status == AllocClientStatus.RUNNING.value
        ], timeout=60)
        alloc = server.store.allocs_by_job(job.namespace, job.id)[0]
        tdir = os.path.join(c.data_dir, alloc.id, "main")
        assert open(os.path.join(tdir, "local", "payload.txt")).read() == (
            "artifact-content"
        )
        assert open(os.path.join(tdir, "local", "config.ini")).read() == (
            f"alloc={alloc.id}"
        )
    finally:
        c.shutdown()
