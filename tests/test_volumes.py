"""Volume lifecycle (VERDICT r4 missing #4 — CSI-equivalent without
external plugin daemons): registration + claim tracking in state, claim
release on terminal allocs (volume watcher), scheduler feasibility against
claims, per-alloc mount plumbing, and /v1/volumes CRUD.

Reference: nomad/csi_endpoint.go, nomad/volumewatcher/volumes_watcher.go,
nomad/state/schema.go csi_volumes table, client volume_hook.go.
"""

from __future__ import annotations

import os
import time

import pytest

from helpers import _wait
from nomad_tpu import mock
from nomad_tpu.api.client import APIClient, APIError
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs.types import (
    AllocClientStatus,
    EvalStatus,
    Volume,
    VolumeMount,
    VolumeRequest,
)


@pytest.fixture
def server():
    s = Server(ServerConfig(
        num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90
    ))
    s.start()
    yield s
    s.shutdown()


def _client(server, tmp_path, name, host_volumes=None) -> Client:
    c = Client(server, ClientConfig(data_dir=str(tmp_path / name)))
    if host_volumes:
        c.node.host_volumes = dict(host_volumes)
    c.start()
    return c


def _vol_job(vol_id, read_only=False, count=1, mount=False):
    job = mock.job()
    tg = job.task_groups[0]
    tg.count = count
    for t in tg.tasks:
        t.resources.cpu = 20
        t.resources.memory_mb = 32
    tg.ephemeral_disk.size_mb = 10
    tg.volumes = {
        "data": VolumeRequest(
            name="data", type="csi", source=vol_id, read_only=read_only
        )
    }
    if mount:
        tg.tasks[0].volume_mounts = [
            VolumeMount(volume="data", destination="data")
        ]
    return job


class TestVolumeState:
    def test_register_claim_release_roundtrip(self, server):
        store = server.store
        vol = Volume(id="vol1", source="disk1")
        store.upsert_volume(server.next_index(), vol)
        assert store.volume_by_id("default", "vol1") is vol

        store.claim_volume(
            server.next_index(), "default", "vol1", "alloc-1", "node-1",
            read_only=False,
        )
        with pytest.raises(ValueError):
            store.delete_volume(server.next_index(), "default", "vol1")
        store.release_volume_claims(
            server.next_index(), "default", "vol1", ["alloc-1"]
        )
        store.delete_volume(server.next_index(), "default", "vol1")
        assert store.volume_by_id("default", "vol1") is None

    def test_reregister_preserves_claims(self, server):
        store = server.store
        store.upsert_volume(server.next_index(), Volume(id="v", source="s"))
        store.claim_volume(
            server.next_index(), "default", "v", "a1", "n1", read_only=False
        )
        store.upsert_volume(
            server.next_index(), Volume(id="v", source="s", capacity_mb=10)
        )
        vol = store.volume_by_id("default", "v")
        assert vol.capacity_mb == 10
        assert vol.write_claims == {"a1": "n1"}
        # ...but the CONTRACT cannot change while claims are live: a new
        # access_mode or source under a held claim is rejected.
        with pytest.raises(ValueError):
            store.upsert_volume(server.next_index(), Volume(
                id="v", source="s", access_mode="multi-node-multi-writer",
            ))
        with pytest.raises(ValueError):
            store.upsert_volume(
                server.next_index(), Volume(id="v", source="other")
            )
        with pytest.raises(ValueError):
            store.claim_volume(
                server.next_index(), "default", "nope", "a2", "n1",
                read_only=False,
            )

    def test_volume_ops_survive_wal_replay(self, tmp_path):
        """Rejected mutations (in-use delete, contract change) must never
        reach the WAL: a journaled-then-raised entry would crash-loop
        replay (validation precedes the journaled twin)."""
        from nomad_tpu.server import Server, ServerConfig

        cfg = ServerConfig(
            num_workers=0, heartbeat_min_ttl=60, heartbeat_max_ttl=90,
            data_dir=str(tmp_path / "srv"),
        )
        srv = Server(cfg)
        srv.start()
        try:
            store = srv.store
            store.upsert_volume(
                srv.next_index(), Volume(id="v1", source="s1")
            )
            store.claim_volume(
                srv.next_index(), "default", "v1", "a1", "n1",
                read_only=False,
            )
            with pytest.raises(ValueError):
                store.delete_volume(srv.next_index(), "default", "v1")
            store.release_volume_claims(
                srv.next_index(), "default", "v1", ["a1"]
            )
            store.upsert_volume(
                srv.next_index(), Volume(id="v2", source="s2")
            )
            store.delete_volume(srv.next_index(), "default", "v2")
        finally:
            srv.shutdown()

        # Restart: replay must reconstruct v1 (with released claims), no
        # v2, and not raise.
        srv2 = Server(ServerConfig(
            num_workers=0, heartbeat_min_ttl=60, heartbeat_max_ttl=90,
            data_dir=str(tmp_path / "srv"),
        ))
        srv2.start()
        try:
            vol = srv2.store.volume_by_id("default", "v1")
            assert vol is not None
            assert not vol.write_claims
            assert srv2.store.volume_by_id("default", "v2") is None
        finally:
            srv2.shutdown()


class TestExclusiveSerialization:
    def test_two_jobs_contending_serialize(self, server, tmp_path):
        """The DONE criterion: two jobs wanting the same single-node-writer
        volume must not run concurrently — the second blocks until the
        first's alloc is terminal and the volume watcher releases its
        claim."""
        client = _client(
            server, tmp_path, "c1", host_volumes={"disk1": str(tmp_path)}
        )
        try:
            server.store.upsert_volume(
                server.next_index(), Volume(id="vol1", source="disk1")
            )

            job1 = _vol_job("vol1")
            job1.task_groups[0].tasks[0].config = {"run_for": 3.0}
            job1.type = "batch"
            ev1 = server.submit_job(job1)
            server.wait_for_eval(ev1.id, timeout=90)
            assert _wait(lambda: any(
                a.client_status == AllocClientStatus.RUNNING.value
                for a in server.store.allocs_by_job("default", job1.id)
            ), timeout=60)
            vol = server.store.volume_by_id("default", "vol1")
            assert len(vol.write_claims) == 1

            # Second writer job: placement must FAIL (blocked eval).
            job2 = _vol_job("vol1")
            job2.task_groups[0].tasks[0].config = {"run_for": 0.1}
            ev2 = server.submit_job(job2)
            done2 = server.wait_for_eval(ev2.id, timeout=90)
            assert done2.status == EvalStatus.COMPLETE.value
            assert not server.store.allocs_by_job("default", job2.id)
            assert server.blocked_evals.blocked_count() >= 1

            # job1 finishes → watcher releases the claim → job2 unblocks
            # and places.
            assert _wait(lambda: bool(
                server.store.allocs_by_job("default", job2.id)
            ), timeout=90), server.store.volume_by_id("default", "vol1")
        finally:
            client.shutdown()

    def test_exemption_narrowed_to_replaced_alloc(self, server, tmp_path):
        """The same-job exemption in volume feasibility is now only for
        the alloc a placement REPLACES.  Registered-after-submission
        ordering, then a destructive update: the replacement must look
        through its predecessor's claim (no deadlock), and the writer
        count must never exceed one."""
        from nomad_tpu.chaos import check_volume_writers

        client = _client(
            server, tmp_path, "c1", host_volumes={"disk1": str(tmp_path)}
        )
        try:
            job = _vol_job("late-vol")
            ev = server.submit_job(job)
            server.wait_for_eval(ev.id, timeout=90)
            # Volume doesn't exist yet: nothing places.
            assert not server.store.allocs_by_job("default", job.id)

            server.store.upsert_volume(
                server.next_index(),
                Volume(id="late-vol", source="disk1"),
            )
            ev = server.submit_job(job)  # re-eval now the volume exists
            server.wait_for_eval(ev.id, timeout=90)

            def live():
                return [
                    a for a in server.store.allocs_by_job(
                        "default", job.id
                    ) if not a.terminal_status()
                ]

            assert _wait(lambda: len(live()) == 1, timeout=60)
            first = live()[0]
            assert _wait(lambda: len(server.store.volume_by_id(
                "default", "late-vol"
            ).write_claims) == 1, timeout=30)

            # Destructive update: the replacement placement must not be
            # blocked by the claim of the very alloc it replaces.
            updated = job.copy()
            updated.task_groups[0].tasks[0].env = {"V": "2"}
            ev = server.submit_job(updated)
            server.wait_for_eval(ev.id, timeout=90)
            assert _wait(
                lambda: live() and all(a.id != first.id for a in live()),
                timeout=60,
            ), "replacement never placed past its predecessor's claim"
            assert len(live()) == 1
            assert check_volume_writers(server.store) == []
        finally:
            client.shutdown()

    def test_readers_share(self, server, tmp_path):
        client = _client(
            server, tmp_path, "c1", host_volumes={"disk1": str(tmp_path)}
        )
        try:
            server.store.upsert_volume(
                server.next_index(),
                Volume(id="vol1", source="disk1"),
            )
            j1 = _vol_job("vol1", read_only=True)
            j2 = _vol_job("vol1", read_only=True)
            for j in (j1, j2):
                ev = server.submit_job(j)
                server.wait_for_eval(ev.id, timeout=90)
            assert _wait(lambda: all(
                server.store.allocs_by_job("default", j.id)
                for j in (j1, j2)
            ), timeout=60)
            vol = server.store.volume_by_id("default", "vol1")
            assert _wait(lambda: len(server.store.volume_by_id(
                "default", "vol1"
            ).read_claims) == 2, timeout=30)
            assert not vol.write_claims
        finally:
            client.shutdown()

    def test_missing_volume_blocks(self, server, tmp_path):
        client = _client(server, tmp_path, "c1")
        try:
            job = _vol_job("nope")
            ev = server.submit_job(job)
            done = server.wait_for_eval(ev.id, timeout=90)
            assert done.status == EvalStatus.COMPLETE.value
            assert not server.store.allocs_by_job("default", job.id)
        finally:
            client.shutdown()


class TestMountPlumbing:
    def test_host_path_linked_into_task_dir(self, server, tmp_path):
        host_dir = tmp_path / "exported"
        host_dir.mkdir()
        (host_dir / "hello.txt").write_text("from the volume")
        client = _client(
            server, tmp_path, "c1",
            host_volumes={"disk1": str(host_dir)},
        )
        try:
            server.store.upsert_volume(
                server.next_index(), Volume(id="vol1", source="disk1")
            )
            job = _vol_job("vol1", mount=True)
            ev = server.submit_job(job)
            server.wait_for_eval(ev.id, timeout=90)
            assert _wait(lambda: any(
                a.client_status == AllocClientStatus.RUNNING.value
                for a in server.store.allocs_by_job("default", job.id)
            ), timeout=60)
            alloc = server.store.allocs_by_job("default", job.id)[0]
            ar = client.allocs[alloc.id]
            link = os.path.join(
                ar.alloc_dir, job.task_groups[0].tasks[0].name, "data"
            )
            assert os.path.islink(link)
            with open(os.path.join(link, "hello.txt")) as fh:
                assert fh.read() == "from the volume"
        finally:
            client.shutdown()


    def test_read_only_mount_cannot_write_host_path(
        self, server, tmp_path
    ):
        """A read_only claimant used to get the same writable symlink as
        a writer.  It must get a write-protected snapshot instead: even a
        privileged task scribbling on the mount never reaches the
        registered host path."""
        import stat

        host_dir = tmp_path / "exported-ro"
        host_dir.mkdir()
        (host_dir / "data.txt").write_text("pristine")
        client = _client(
            server, tmp_path, "c1",
            host_volumes={"diskro": str(host_dir)},
        )
        try:
            server.store.upsert_volume(
                server.next_index(), Volume(id="volro", source="diskro")
            )
            job = _vol_job("volro", read_only=True, mount=True)
            ev = server.submit_job(job)
            server.wait_for_eval(ev.id, timeout=90)
            assert _wait(lambda: any(
                a.client_status == AllocClientStatus.RUNNING.value
                for a in server.store.allocs_by_job("default", job.id)
            ), timeout=60)
            alloc = server.store.allocs_by_job("default", job.id)[0]
            ar = client.allocs[alloc.id]
            mnt = os.path.join(
                ar.alloc_dir, job.task_groups[0].tasks[0].name, "data"
            )
            inner = os.path.join(mnt, "data.txt")
            # Not a symlink into the host path — a snapshot copy.
            assert not os.path.islink(mnt)
            with open(inner) as fh:
                assert fh.read() == "pristine"
            # Write bits stripped (early EACCES for unprivileged tasks).
            assert not os.stat(inner).st_mode & stat.S_IWUSR
            # Even forcing a write onto the mount leaves the host intact.
            os.chmod(inner, 0o644)
            with open(inner, "w") as fh:
                fh.write("scribble")
            assert (host_dir / "data.txt").read_text() == "pristine"
        finally:
            client.shutdown()


class TestVolumeHTTP:
    def test_crud_over_http(self, tmp_path):
        from nomad_tpu.api import Agent, AgentConfig

        a = Agent(AgentConfig(
            server_config=ServerConfig(
                num_workers=1, heartbeat_min_ttl=60, heartbeat_max_ttl=90
            ),
            client_config=ClientConfig(data_dir=str(tmp_path / "c")),
        ))
        a.start()
        try:
            api = APIClient(a.rpc_addr)
            out = api.register_volume({
                "ID": "shared", "Source": "disk9",
                "AccessMode": "multi-node-reader",
            })
            assert out["ID"] == "shared"
            vols = api.list_volumes()
            assert [v["id"] for v in vols] == ["shared"]
            got = api.get_volume("shared")
            assert got["source"] == "disk9"
            api.deregister_volume("shared")
            with pytest.raises(APIError):
                api.get_volume("shared")
        finally:
            a.shutdown()
