"""Periodic re-fingerprint (client/fingerprint_manager.go) + client
host/device stats (ClientStats surface)."""

from __future__ import annotations

import time

import pytest

from helpers import _wait
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.server import Server, ServerConfig


@pytest.fixture
def server():
    s = Server(ServerConfig(
        num_workers=1, heartbeat_min_ttl=60, heartbeat_max_ttl=90
    ))
    s.start()
    yield s
    s.shutdown()


def test_refingerprint_pushes_changed_facts(server, tmp_path, monkeypatch):
    # Start WITHOUT an accelerator in the environment (the suite's env may
    # carry the TPU-tunnel vars).
    monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
    monkeypatch.delenv("TPU_ACCELERATOR_TYPE", raising=False)
    c = Client(server, ClientConfig(
        data_dir=str(tmp_path / "c"), fingerprint_interval=0.2
    ))
    c.start()
    try:
        node_id = c.node.id
        assert "platform.tpu.type" not in (
            server.store.node_by_id(node_id).attributes
        )
        # An accelerator appears (env-fingerprinted TPU).
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5e-8")
        assert _wait(lambda: server.store.node_by_id(
            node_id
        ).attributes.get("platform.tpu.type") == "v5e", timeout=15)
        assert "tpu" in server.store.node_by_id(node_id).resources.devices
    finally:
        c.shutdown()


def test_client_stats_endpoint(tmp_path):
    from nomad_tpu.api import Agent, AgentConfig
    from nomad_tpu.api.client import APIClient

    a = Agent(AgentConfig(
        server_config=ServerConfig(
            num_workers=1, heartbeat_min_ttl=60, heartbeat_max_ttl=90
        ),
        client_config=ClientConfig(data_dir=str(tmp_path / "c")),
    ))
    a.start()
    try:
        out = APIClient(a.rpc_addr)._call("GET", "/v1/client/stats")
        assert out["CPU"]["Cores"] >= 1
        assert out["DataDir"]["Total"] > 0
        assert out["AllocCount"] == 0
        assert "Devices" in out
    finally:
        a.shutdown()


def test_reregistration_preserves_operator_state(server, tmp_path, monkeypatch):
    """A re-fingerprint re-registration must NOT wipe server-owned node
    state: a drain in progress (or markings like ineligibility) survives
    the client pushing refreshed facts (Node.Register semantics)."""
    from nomad_tpu.structs.types import DrainStrategy

    monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
    monkeypatch.delenv("TPU_ACCELERATOR_TYPE", raising=False)
    c = Client(server, ClientConfig(
        data_dir=str(tmp_path / "c"), fingerprint_interval=0.2
    ))
    c.start()
    try:
        node_id = c.node.id
        server.update_node_drain(
            node_id, DrainStrategy(deadline=300.0)
        )
        assert server.store.node_by_id(node_id).drain
        # Trigger a fact change -> re-registration.
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-4")
        assert _wait(lambda: server.store.node_by_id(
            node_id
        ).attributes.get("platform.tpu.type") == "v5p", timeout=15)
        node = server.store.node_by_id(node_id)
        assert node.drain  # drain survived the re-register
        assert node.scheduling_eligibility == "ineligible"
    finally:
        c.shutdown()
