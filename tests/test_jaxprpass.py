"""The jaxpr-level contract gate, tested from both sides.

One half proves the analyzer itself: five mutant entry points — an
injected ``io_callback``, a full-score-vector return, a node-axis value
pushed through a collective, a dropped donation, an occupancy-keyed
static arg — each built to violate exactly ONE of J101–J105 while
honoring every other contract clause, so each test asserts the rule set
is precisely ``{its rule}``.  A clean twin asserts the empty set, so a
check that started firing spuriously is caught the same way as one that
went blind.

The other half is the live gate: the real contract table
(:mod:`nomad_tpu.lint.contracts`) runs against the real tree, riding
tier-1 alongside ``tests/test_lint_gate.py``, including the acceptance
claim that ONE compile of ``fused_place_batch_live`` serves every
occupancy fill (measured from the real compile cache, not inferred).
"""

from __future__ import annotations

import functools

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import jax.numpy as jnp  # noqa: E402

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P  # noqa: E402

from nomad_tpu.lint import load_baseline, repo_root, split_baselined  # noqa: E402
from nomad_tpu.lint import contracts, jaxprpass  # noqa: E402
from nomad_tpu.lint.contracts import DeviceContract, Grid  # noqa: E402
from nomad_tpu.parallel.sharding import make_mesh  # noqa: E402

pytestmark = pytest.mark.skipif(
    not jaxprpass.available(), reason="no JAX backend"
)

# ---------------------------------------------------------------------------
# The mini entry-point family: same contract shape as the fused kernel
# (node-axis operand, per-lane operands, lane mask, (B, 1) packed result)
# at a fraction of the trace/compile cost.
# ---------------------------------------------------------------------------

N1, N2 = 37, 53  # prime markers: collide with no other dimension


def mini_operands(g: Grid):
    cols = np.ones((g.nodes, 3), np.float32)  # node-axis resident operand
    ops = np.ones((g.batch, 4), np.float32)  # per-lane operand (donated)
    lane_mask = np.zeros((g.batch,), bool)
    lane_mask[: g.live] = True
    return (cols, ops, lane_mask)


def _mini_body(cols, ops, lane_mask):
    w = jnp.where(lane_mask[:, None], ops, 0.0)
    return w.sum(axis=1, keepdims=True) + 0.0 * cols.sum()  # (B, 1)


TRACE_GRIDS = (
    Grid(nodes=N1, batch=4, placements=1, deltas=1, live=4),
    Grid(nodes=N2, batch=4, placements=1, deltas=1, live=4),
)
COMPILE_GRID = Grid(nodes=16, batch=4, placements=1, deltas=1, live=4)


def mini_contract(build, **over) -> DeviceContract:
    kw = dict(
        name="mini",
        path="tests/test_jaxprpass.py",
        build=build,
        operands=mini_operands,
        static_kwargs=lambda g: {},
        trace_grids=TRACE_GRIDS,
        out_budget=lambda g: g.batch * 4,  # the (B, 1) f32 verdict column
        donated_args=(1, 2),
        compile_grid=COMPILE_GRID,
        sweep=contracts.occupancy_sweep,
        max_compiles=1,
    )
    kw.update(over)
    return DeviceContract(**kw)


def rules(findings):
    return {f.rule for f in findings}


def test_clean_mini_entry_fires_nothing():
    entry = jax.jit(_mini_body, donate_argnums=(1, 2))
    fs = jaxprpass.check_contract(mini_contract(lambda g: entry))
    assert rules(fs) == set(), [f.render() for f in fs]


def test_j101_injected_io_callback_fires_only_j101():
    from jax.experimental import io_callback

    def body(cols, ops, lane_mask):
        io_callback(lambda a: None, None, ops)  # the host round trip
        return _mini_body(cols, ops, lane_mask)

    entry = jax.jit(body, donate_argnums=(1, 2))
    fs = jaxprpass.check_contract(mini_contract(lambda g: entry))
    assert rules(fs) == {"J101"}, [f.render() for f in fs]


def test_j102_full_score_vector_return_fires_only_j102():
    def body(cols, ops, lane_mask):
        # The classic regression: "just return the scores too" — an O(N)
        # value through the device→host tunnel, on every launch.
        return _mini_body(cols, ops, lane_mask), cols.sum(axis=1)

    entry = jax.jit(body, donate_argnums=(1, 2))
    fs = jaxprpass.check_contract(mini_contract(lambda g: entry))
    assert rules(fs) == {"J102"}, [f.render() for f in fs]
    # Both halves of J102 must have fired: over budget AND node-dependent.
    msgs = " | ".join(f.message for f in fs)
    assert "budget" in msgs and "node count" in msgs


def test_j103_node_axis_collective_fires_only_j103():
    mesh = make_mesh(1, batch=1)

    def local(cols, ops, lane_mask):
        # An (n_local,)-shaped value pushed through a collective: the
        # mesh moves O(N) bytes per launch however small the result.
        leak = jax.lax.psum(cols[:, 0], "batch")
        anchor = jax.lax.pmax(leak.sum(), "node")
        w = jnp.where(lane_mask[:, None], ops, 0.0)
        return w.sum(axis=1, keepdims=True) + 0.0 * anchor

    entry = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P("node", None), P("batch", None), P("batch")),
            out_specs=P("batch", None),
        )
    )
    fs = jaxprpass.check_contract(
        mini_contract(lambda g: entry, donated_args=())
    )
    assert rules(fs) == {"J103"}, [f.render() for f in fs]


def test_j104_dropped_donation_fires_only_j104():
    entry = jax.jit(_mini_body)  # donate_argnums went missing in a refactor
    fs = jaxprpass.check_contract(mini_contract(lambda g: entry))
    assert rules(fs) == {"J104"}, [f.render() for f in fs]


def test_j104_undeclared_donation_fires_only_j104():
    entry = jax.jit(_mini_body, donate_argnums=(0, 1, 2))  # cols is shared!
    fs = jaxprpass.check_contract(mini_contract(lambda g: entry))
    assert rules(fs) == {"J104"}, [f.render() for f in fs]


def test_j105_occupancy_keyed_static_arg_fires_only_j105():
    @functools.partial(
        jax.jit, static_argnames=("n_live",), donate_argnums=(1, 2)
    )
    def body(cols, ops, lane_mask, *, n_live):
        # Occupancy in the static key: every fill level recompiles.
        w = ops[:n_live]
        base = jnp.where(lane_mask[:, None], ops, 0.0)
        return base.sum(axis=1, keepdims=True) + w.sum() + 0.0 * cols.sum()

    fs = jaxprpass.check_contract(
        mini_contract(
            lambda g: body,
            static_kwargs=lambda g: {"n_live": int(g.live)},
        )
    )
    assert rules(fs) == {"J105"}, [f.render() for f in fs]


def test_j103_catches_the_j005_helper_evasion():
    """Companion to tests/test_lint.py (TestJ005NodeAxisFetch): threading
    the node-axis value through ONE helper function defeats the AST
    rule's local-variable tracking — but the traced program still shows
    an N-shaped output escaping the mesh boundary, whatever the call
    graph looked like.  This is why both layers exist."""
    mesh = make_mesh(1, batch=1)

    def _snapshot(x):  # the one-hop indirection J005 cannot see through
        return x * 2.0

    def local(cols, ops, lane_mask):
        w = jnp.where(lane_mask[:, None], ops, 0.0)
        verdict = w.sum(axis=1, keepdims=True) + 0.0 * jax.lax.pmax(
            cols.sum(), "node"
        )
        return verdict, _snapshot(cols)

    entry = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P("node", None), P("batch", None), P("batch")),
            out_specs=(P("batch", None), P("node", None)),
        )
    )
    fs = jaxprpass.check_contract(
        mini_contract(
            lambda g: entry,
            donated_args=(),
            out_budget=None,  # isolate the boundary check
            sweep=None,
            max_compiles=None,
            compile_grid=None,
        )
    )
    assert rules(fs) == {"J103"}, [f.render() for f in fs]
    assert any("escapes the mesh boundary" in f.message for f in fs)


def test_harness_breakage_surfaces_as_j100():
    def broken_build(g):
        raise RuntimeError("entry point renamed out from under the table")

    fs = jaxprpass.check_contract(mini_contract(broken_build))
    assert rules(fs) == {"J100"}


# ---------------------------------------------------------------------------
# The live gate: real contract table vs the real tree.
# ---------------------------------------------------------------------------


def test_live_tree_contracts_clean_against_baseline():
    findings = jaxprpass.run(repo_root())
    new, _suppressed, _stale = split_baselined(findings, load_baseline())
    assert new == [], "jaxpr contract findings:\n" + "\n".join(
        f.render() for f in new
    )


def test_j105_one_compile_serves_all_occupancy_fills():
    """The acceptance claim, asserted from the real compile cache: the
    live fused entry's occupancy sweep (fill 1..B) costs at most one new
    cache entry — lane occupancy is runtime data, never a static key."""
    c = contracts.get("fused_place_batch_live")
    assert c.max_compiles == 1
    entry = c.build(c.compile_grid)
    measured = contracts.occupancy_sweep(entry, c)
    assert measured <= 1, f"occupancy sweep cost {measured} compiles"


def test_contract_table_names_every_registered_entry():
    names = {c.name for c in contracts.table()}
    assert names == {
        "fused_place_batch",
        "fused_place_batch_live",
        "sharded_fused_place_batch",
        "make_row_scatter",
    }
