"""Dispatch coalescer (VERDICT r3 item 2): concurrent selects batch into
single device dispatches; results match the solo path; the live server
schedules through it."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from helpers import _client, _small, _wait
from nomad_tpu import mock
from nomad_tpu.scheduler.coalescer import DeviceCoalescer, MAX_DELTA_ROWS
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.state import NodeMatrix
from nomad_tpu.structs.types import AllocClientStatus


def _matrix(n=8):
    m = NodeMatrix(capacity=16)
    for i in range(n):
        m.upsert_node(mock.node())
    return m


def _inputs(m, job):
    from nomad_tpu.ops.encode import RequestEncoder

    enc = RequestEncoder(m)
    tg = job.task_groups[0]
    compiled = enc.compile(job, tg)
    n = m.capacity
    return dict(
        request=compiled.request,
        delta_rows=np.full((MAX_DELTA_ROWS,), -1, np.int32),
        delta_vals=np.zeros((MAX_DELTA_ROWS, 3), np.float32),
        tg_count=np.zeros((n,), np.int32),
        spread_counts=np.zeros_like(compiled.request.s_desired),
        penalty=np.zeros((n,), bool),
        class_elig=np.ones((2,), bool),
        host_mask=np.ones((n,), bool),
    )


class TestDeviceCoalescer:
    def test_concurrent_places_coalesce_and_match(self):
        m = _matrix()
        coal = DeviceCoalescer(m, max_lanes=8, linger_s=0.02)
        coal.start()
        try:
            jobs = [mock.job() for _ in range(6)]
            for i, j in enumerate(jobs):
                j.task_groups[0].tasks[0].resources.cpu = 100 + 50 * i
            results = {}
            errors = []

            def run(i, j):
                try:
                    results[i] = coal.place(**_inputs(m, j))
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)

            threads = [
                threading.Thread(target=run, args=(i, j))
                for i, j in enumerate(jobs)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not errors, errors
            assert len(results) == 6
            # Coalescing happened (strictly fewer dispatches than requests;
            # an exact count would be timing-dependent on loaded machines).
            assert coal.dispatches < 6, coal.dispatches
            assert coal.coalesced_requests == 6
            for i, out in results.items():
                assert out.rows.shape[0] == coal.scan_length
                assert (out.rows[:1] >= 0).all(), f"request {i} failed"
        finally:
            coal.stop()

    def test_inert_lane_padding_places_nothing(self):
        m = _matrix()
        coal = DeviceCoalescer(m, max_lanes=4, linger_s=0.0)
        coal.start()
        try:
            out = coal.place(**_inputs(m, mock.job()))
            assert (out.rows[:1] >= 0).all()
        finally:
            coal.stop()

    def test_capacity_growth_mid_queue(self):
        """A request built before matrix growth still dispatches (padded,
        new rows masked off)."""
        m = _matrix(4)
        coal = DeviceCoalescer(m, max_lanes=4, linger_s=0.05)
        coal.start()
        try:
            inp = _inputs(m, mock.job())
            got = {}

            def submit():
                got["out"] = coal.place(**inp)

            t = threading.Thread(target=submit)
            t.start()
            # Grow the matrix while the request lingers in the queue.
            for _ in range(20):
                m.upsert_node(mock.node())
            t.join(timeout=120)
            assert "out" in got
            assert int(got["out"].rows[0]) < 4 or int(got["out"].rows[0]) == -1
        finally:
            coal.stop()


def test_server_schedules_through_coalescer(tmp_path):
    srv = Server(ServerConfig(
        num_workers=4, heartbeat_min_ttl=60, heartbeat_max_ttl=90
    ))
    srv.start()
    c = _client(srv, tmp_path, "c1")
    try:
        jobs = [_small(mock.job()) for _ in range(8)]
        for j in jobs:
            # 8 jobs x 2 allocs x 20cpu = 320 — fits the single mock node.
            j.task_groups[0].count = 2
        evals = [srv.submit_job(j) for j in jobs]
        for ev in evals:
            assert srv.wait_for_eval(ev.id, timeout=120) is not None
        assert srv.coalescer.dispatches > 0
        assert srv.coalescer.coalesced_requests >= 8
        for j in jobs:
            assert _wait(lambda j=j: [
                a for a in srv.store.allocs_by_job(j.namespace, j.id)
                if a.client_status == AllocClientStatus.RUNNING.value
            ], timeout=60)
    finally:
        c.shutdown()
        srv.shutdown()
