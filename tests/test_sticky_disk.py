"""Ephemeral-disk sticky/migrate (VERDICT r3 missing item 7).

Reference: findPreferredNode (scheduler/generic_sched.go:756-770) places
sticky replacements on the previous alloc's node; the prev-alloc watcher
(client/allocwatcher/) carries the disk data into the new alloc dir.
"""

from __future__ import annotations

import os

import pytest

from helpers import _client, _small, _wait
from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs.types import AllocClientStatus, Task


@pytest.fixture
def server():
    s = Server(ServerConfig(
        num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90
    ))
    s.start()
    yield s
    s.shutdown()


def _sticky_job(marker: str):
    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 2
    tg.ephemeral_disk.sticky = True
    tg.ephemeral_disk.migrate = True
    tg.ephemeral_disk.size_mb = 10
    tg.tasks = [Task(
        name="main", driver="raw_exec",
        config={
            "command": "/bin/sh",
            "args": [
                "-c",
                f'echo {marker} >> "$NOMAD_TASK_DIR/local/state.txt"; '
                "sleep 300",
            ],
        },
    )]
    for t in tg.tasks:
        t.resources.cpu = 20
        t.resources.memory_mb = 32
    return job


def _running(server, job, version=None, n=2, timeout=60):
    def ready():
        allocs = [
            a for a in server.store.allocs_by_job(job.namespace, job.id)
            if a.client_status == AllocClientStatus.RUNNING.value
            and (version is None
                 or (a.job is not None and a.job.version == version))
        ]
        return allocs if len(allocs) == n else None
    assert _wait(lambda: ready() is not None, timeout=timeout)
    return ready()


def test_sticky_replacement_stays_on_node_and_keeps_data(server, tmp_path):
    c1 = _client(server, tmp_path, "c1")
    c2 = _client(server, tmp_path, "c2")
    try:
        job = _sticky_job("v0")
        ev = server.submit_job(job)
        server.wait_for_eval(ev.id, timeout=90)
        originals = _running(server, job, version=0)
        node_of = {a.id: a.node_id for a in originals}

        # Destructive update → replacements.
        job2 = job.copy()
        job2.task_groups = [job2.task_groups[0]]
        job2.task_groups[0].tasks[0].env = {"V": "2"}
        ev2 = server.submit_job(job2)
        server.wait_for_eval(ev2.id, timeout=90)
        replacements = _running(server, job, version=1)

        for a in replacements:
            assert a.previous_allocation in node_of
            # Sticky: same node as the alloc it replaced.
            assert a.node_id == node_of[a.previous_allocation], (
                a.node_id, node_of[a.previous_allocation]
            )
            # Migrate: the previous alloc's local data came along.
            client = c1 if a.node_id == c1.node.id else c2
            state = os.path.join(
                client.data_dir, a.id, "main", "local", "state.txt"
            )
            assert _wait(lambda s=state: os.path.exists(s), timeout=15)
            content = open(state).read()
            assert "v0" in content, content  # inherited from predecessor
    finally:
        c1.shutdown()
        c2.shutdown()


def test_non_sticky_placement_unrestricted(server, tmp_path):
    """Control: without sticky, replacements place wherever binpack says
    (no restriction failure either way — just no crash and full count)."""
    c1 = _client(server, tmp_path, "c1")
    try:
        job = _sticky_job("x")
        job.task_groups[0].ephemeral_disk.sticky = False
        job.task_groups[0].ephemeral_disk.migrate = False
        ev = server.submit_job(job)
        server.wait_for_eval(ev.id, timeout=90)
        assert _running(server, job, version=0)
    finally:
        c1.shutdown()


def test_cross_node_migration_via_fs_api(tmp_path):
    """VERDICT r4 missing #9: drain a node; a sticky+migrate group's data
    follows the replacement to a DIFFERENT node, fetched over the origin
    agent's FS API (client/allocwatcher remote prevAllocMigrator)."""
    import socket
    import time as _time

    from nomad_tpu.api.agent import Agent, AgentConfig
    from nomad_tpu.client import ClientConfig
    from nomad_tpu.structs.types import DrainStrategy

    def port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    sp = port()
    srv_agent = Agent(AgentConfig(
        name="srv", server_enabled=True, client_enabled=False,
        http_host="127.0.0.1", http_port=sp,
        server_config=ServerConfig(
            num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90
        ),
    ))
    srv_agent.start()
    agents = [srv_agent]
    try:
        clients = []
        for name in ("c1", "c2"):
            a = Agent(AgentConfig(
                name=name, server_enabled=False, client_enabled=True,
                http_host="127.0.0.1", http_port=port(),
                server_addr=f"http://127.0.0.1:{sp}",
                client_config=ClientConfig(
                    data_dir=str(tmp_path / name)
                ),
            ))
            a.start()
            agents.append(a)
            clients.append(a)
        srv = srv_agent.server

        job = _sticky_job("generation-1")
        job.task_groups[0].count = 1
        ev = srv.submit_job(job)
        srv.wait_for_eval(ev.id, timeout=90)
        assert _running(srv, job, n=1)
        first = [
            a for a in srv.store.allocs_by_job(job.namespace, job.id)
            if a.client_status == AllocClientStatus.RUNNING.value
        ][0]
        origin = next(
            c for c in clients if c.client.node.id == first.node_id
        )
        # Let the task write its marker.
        marker = os.path.join(
            origin.client.data_dir, first.id, "main", "local", "state.txt"
        )
        assert _wait(lambda: os.path.exists(marker), timeout=30)

        # Drain the origin node: the replacement must land on the OTHER
        # node and carry the data over the wire.
        srv.update_node_drain(
            first.node_id,
            DrainStrategy(
                deadline=120.0, force_deadline=_time.time() + 120.0
            ),
        )
        srv.drainer.notify()

        def replacement():
            return [
                a for a in srv.store.allocs_by_job(job.namespace, job.id)
                if a.id != first.id
                and a.client_status == AllocClientStatus.RUNNING.value
            ]
        assert _wait(lambda: bool(replacement()), timeout=90)
        newalloc = replacement()[0]
        assert newalloc.node_id != first.node_id
        assert newalloc.previous_allocation == first.id
        dest = next(
            c for c in clients if c.client.node.id == newalloc.node_id
        )
        carried = os.path.join(
            dest.client.data_dir, newalloc.id, "main", "local", "state.txt"
        )
        assert _wait(lambda: os.path.exists(carried), timeout=60)
        with open(carried) as fh:
            content = fh.read()
        assert "generation-1" in content
    finally:
        for a in reversed(agents):
            try:
                a.shutdown()
            except Exception:  # noqa: BLE001
                pass


def test_migration_cap_charged_against_bytes_read(tmp_path):
    """ADVICE r5: the migration byte cap must be charged against bytes
    actually READ — an origin that under-reports Size (or ignores the
    limit param) cannot stream past REMOTE_MIGRATE_CAP and fill this
    node's disk."""
    import http.server
    import json
    import threading

    from nomad_tpu.client.allocrunner import AllocRunner
    from nomad_tpu.client.driver import DriverRegistry

    cap = 64 * 1024

    class LyingOrigin(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if "/fs/ls/" in self.path:
                body = json.dumps([
                    {"Name": "state.bin", "IsDir": False, "Size": 10},
                ]).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            # cat: advertise 10 bytes above, stream 64x the cap.
            total = cap * 64
            self.send_response(200)
            self.send_header("Content-Length", str(total))
            self.end_headers()
            block = b"\0" * 65536
            try:
                for _ in range(total // len(block)):
                    self.wfile.write(block)
            except (BrokenPipeError, ConnectionResetError):
                pass  # the capped client hung up — expected

        def log_message(self, *args):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), LyingOrigin)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    addr = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        job = mock.job()
        tg = job.task_groups[0]
        alloc = mock.alloc(job)
        alloc.previous_allocation = "prev0000"
        ar = AllocRunner(
            alloc, DriverRegistry(), str(tmp_path / "data"),
            on_alloc_update=lambda _ar: None,
            alloc_fs_origin=lambda _pid: {"Addr": addr, "Terminal": True},
        )
        ar.REMOTE_MIGRATE_CAP = cap
        os.makedirs(ar.alloc_dir, exist_ok=True)
        ar._migrate_remote_disk(tg)
        # The transfer aborted at the cap and the partial file was
        # dropped — nothing oversized reached disk.
        for root, _dirs, files in os.walk(ar.alloc_dir):
            for f in files:
                path = os.path.join(root, f)
                assert os.path.getsize(path) <= cap, path
            assert "state.bin" not in files
    finally:
        httpd.shutdown()
