"""Executor sidecar process boundary (VERDICT r3 item 6).

Reference: go-plugin's process isolation + reattach
(plugins/drivers/driver.go:47-65, drivers/shared/executor/): a driver or
agent crash must not take tasks down, and kill -9 of the supervisor
itself must be recoverable.
"""

from __future__ import annotations

import os
import signal
import time

import pytest

from helpers import _crash_client, _wait
from nomad_tpu import mock
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs.types import AllocClientStatus, Task


@pytest.fixture(autouse=True)
def _python_sidecar(monkeypatch):
    # This file covers the PYTHON sidecar; the native C++ one (preferred
    # automatically when built) has its own suite, test_native_executor.py.
    monkeypatch.setenv("NOMAD_TPU_EXECUTOR_BIN", "")


@pytest.fixture
def server():
    s = Server(ServerConfig(
        num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90
    ))
    s.start()
    yield s
    s.shutdown()


def _exec_job(command, args, **task_cfg):
    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 1
    tg.tasks = [Task(
        name="main", driver="exec",
        config={"command": command, "args": list(args), **task_cfg},
    )]
    for t in tg.tasks:
        t.resources.cpu = 20
        t.resources.memory_mb = 32
    tg.ephemeral_disk.size_mb = 10
    return job


def _running_alloc(server, job, timeout=60):
    assert _wait(lambda: [
        a for a in server.store.allocs_by_job(job.namespace, job.id)
        if a.client_status == AllocClientStatus.RUNNING.value
    ], timeout=timeout)
    return server.store.allocs_by_job(job.namespace, job.id)[0]


def _sidecar_pid(client) -> int:
    sc = client.drivers.get("exec")._sidecar
    assert sc is not None
    out = sc.call("ping")
    return int(out["pid"])


def _dead_or_zombie(pid: int) -> bool:
    """A SIGKILLed child stays visible in /proc as a zombie until reaped —
    'gone' means no process OR state Z."""
    try:
        with open(f"/proc/{pid}/stat") as fh:
            return fh.read().split(")")[-1].split()[0] == "Z"
    except OSError:
        return True


def test_exec_task_runs_in_own_session(server, tmp_path):
    c = Client(server, ClientConfig(data_dir=str(tmp_path / "c")))
    c.start()
    try:
        job = _exec_job("/bin/sleep", ["300"])
        server.submit_job(job)
        alloc = _running_alloc(server, job)
        handle = c.allocs[alloc.id].runners["main"].handle
        pid = handle.pid
        assert pid > 0 and os.path.exists(f"/proc/{pid}")
        # setsid isolation: the task leads its own session, distinct from
        # both the agent's and the sidecar's.
        assert os.getsid(pid) == pid
        assert os.getsid(pid) != os.getsid(os.getpid())
        # The task is a child of the SIDECAR, not the agent.
        with open(f"/proc/{pid}/status") as fh:
            ppid = int(
                next(l for l in fh if l.startswith("PPid:")).split()[1]
            )
        assert ppid == _sidecar_pid(c)
        assert ppid != os.getpid()
    finally:
        c.shutdown()


def test_rlimits_applied(server, tmp_path):
    c = Client(server, ClientConfig(data_dir=str(tmp_path / "c")))
    c.start()
    try:
        job = _exec_job(
            "/bin/sh", ["-c", "ulimit -n; sleep 300"],
            rlimits={"nofile": 64},
        )
        server.submit_job(job)
        alloc = _running_alloc(server, job)
        ar = c.allocs[alloc.id]
        stdout = os.path.join(ar.alloc_dir, "main", "main.stdout")
        assert _wait(
            lambda: os.path.exists(stdout) and open(stdout).read().strip(),
            timeout=15,
        )
        assert open(stdout).read().strip() == "64"
    finally:
        c.shutdown()


def test_sidecar_kill9_task_survives_and_recovers(server, tmp_path):
    """THE acceptance test: kill -9 the sidecar; the task keeps running;
    the agent's next driver op respawns a sidecar that re-adopts the task
    by pid; stopping the task still works."""
    c = Client(server, ClientConfig(data_dir=str(tmp_path / "c")))
    c.start()
    try:
        job = _exec_job("/bin/sleep", ["300"])
        server.submit_job(job)
        alloc = _running_alloc(server, job)
        handle = c.allocs[alloc.id].runners["main"].handle
        task_pid = handle.pid
        old_sidecar = _sidecar_pid(c)

        os.kill(old_sidecar, signal.SIGKILL)
        assert _wait(lambda: _dead_or_zombie(old_sidecar), timeout=10)
        # The task survived the supervisor's death (setsid + detach).
        assert os.path.exists(f"/proc/{task_pid}")
        assert not _dead_or_zombie(task_pid)

        # The driver's next op transparently respawns + recovers.
        sc = c.drivers.get("exec")._sidecar
        out = sc.call("wait", id=handle.id)
        assert out.get("running"), out
        new_sidecar = _sidecar_pid(c)
        assert new_sidecar != old_sidecar
        assert os.path.exists(f"/proc/{task_pid}")  # never restarted

        # Supervision is live again: kill the task, the runner notices and
        # the restart policy produces a replacement process.
        os.kill(task_pid, signal.SIGKILL)
        ar = c.allocs[alloc.id]
        assert _wait(
            lambda: ar.task_states["main"].restarts > 0 or ar.terminal,
            timeout=60,
        )
    finally:
        c.shutdown()


def test_agent_restart_reattaches_through_sidecar(server, tmp_path):
    """Agent crash: both the sidecar and the task outlive it; the new
    agent re-attaches through the sidecar protocol (RecoverTask)."""
    data_dir = str(tmp_path / "c")
    c1 = Client(server, ClientConfig(data_dir=data_dir))
    c1.start()
    job = _exec_job("/bin/sleep", ["300"])
    server.submit_job(job)
    alloc = _running_alloc(server, job)
    pid = c1.allocs[alloc.id].runners["main"].handle.pid
    sidecar = _sidecar_pid(c1)
    _crash_client(c1)
    time.sleep(0.3)
    assert os.path.exists(f"/proc/{pid}")
    assert os.path.exists(f"/proc/{sidecar}")

    c2 = Client(server, ClientConfig(data_dir=data_dir))
    assert c2.node.id == c1.node.id
    c2.start()
    try:
        assert _wait(lambda: alloc.id in c2.allocs, timeout=30)
        ar2 = c2.allocs[alloc.id]
        assert _wait(lambda: "main" in ar2.runners
                     and ar2.runners["main"].handle is not None, timeout=30)
        assert ar2.runners["main"].handle.pid == pid
        assert os.path.exists(f"/proc/{pid}")  # never restarted
        assert _wait(
            lambda: ar2.client_status == AllocClientStatus.RUNNING.value,
            timeout=30,
        )
    finally:
        c2.shutdown()
