// nomad_tpu native executor sidecar.
//
// The native-runtime half of the exec driver's process boundary: a C++
// re-implementation of nomad_tpu/client/executor.py speaking the exact
// same newline-delimited-JSON protocol over a unix socket, so
// client/driver.py's SidecarClient can spawn either interchangeably
// (reference analog: drivers/shared/executor/ is compiled Go supervising
// tasks behind gRPC; here the supervisor is native C++ and the wire is
// JSON lines).
//
// Ops (one JSON object per line):
//   ping                                -> {pong: true, pid}
//   start {id, argv, env, cwd, stdout, stderr, rlimits{}, cgroup}
//                                       -> {pid, start_ts}
//   wait {id}                           -> {running} | {exit_code, signal}
//   stop {id, grace}                    -> {}
//   destroy {id}                        -> {}
//   recover {id, pid, start_ts}         -> {ok}
//   list                                -> {tasks: {id: {...}}}
//   shutdown                            -> {} (exits; tasks keep running)
//
// Isolation on start: setsid (own session -> group kills), RLIMIT_* from
// the request, best-effort cgroup v2 scope.  State: every mutation
// rewrites <state-dir>/executor.state.json so a replacement sidecar can
// recover supervised pids after kill -9.
//
// Build: make -C native   (g++ -std=c++17 -pthread; no dependencies)

#include <fcntl.h>
#include <signal.h>
#include <sys/resource.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/un.h>
#include <sys/wait.h>
#include <unistd.h>

#include <cctype>
#include <cmath>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

// ---------------------------------------------------------------------------
// Minimal JSON (objects, arrays, strings, numbers, bools, null) — enough
// for this protocol; no external dependencies.
// ---------------------------------------------------------------------------

struct Json {
  enum Type { NUL, BOOL, NUM, STR, ARR, OBJ } type = NUL;
  bool b = false;
  double num = 0;
  std::string str;
  std::vector<Json> arr;
  std::map<std::string, Json> obj;

  static Json S(const std::string& s) {
    Json j; j.type = STR; j.str = s; return j;
  }
  static Json N(double d) { Json j; j.type = NUM; j.num = d; return j; }
  static Json B(bool v) { Json j; j.type = BOOL; j.b = v; return j; }
  static Json O() { Json j; j.type = OBJ; return j; }

  bool has(const std::string& k) const { return obj.count(k) > 0; }
  const Json& at(const std::string& k) const {
    static Json null;
    auto it = obj.find(k);
    return it == obj.end() ? null : it->second;
  }
  std::string s(const std::string& k, const std::string& d = "") const {
    const Json& v = at(k);
    return v.type == STR ? v.str : d;
  }
  double n(const std::string& k, double d = 0) const {
    const Json& v = at(k);
    return v.type == NUM ? v.num : d;
  }
  bool truthy(const std::string& k) const {
    const Json& v = at(k);
    return (v.type == BOOL && v.b) || (v.type == NUM && v.num != 0) ||
           (v.type == STR && !v.str.empty());
  }
};

struct Parser {
  const char* p;
  const char* end;
  bool ok = true;

  explicit Parser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}

  void ws() { while (p < end && isspace((unsigned char)*p)) ++p; }
  bool eat(char c) {
    ws();
    if (p < end && *p == c) { ++p; return true; }
    return false;
  }

  Json parse() {
    ws();
    if (p >= end) { ok = false; return {}; }
    switch (*p) {
      case '{': return object();
      case '[': return array();
      case '"': return string_();
      case 't': case 'f': return boolean();
      case 'n': p += 4; return {};
      default: return number();
    }
  }

  Json object() {
    Json j; j.type = Json::OBJ;
    ++p;  // {
    ws();
    if (eat('}')) return j;
    while (ok) {
      ws();
      if (p >= end || *p != '"') { ok = false; break; }
      Json key = string_();
      if (!eat(':')) { ok = false; break; }
      j.obj[key.str] = parse();
      if (eat(',')) continue;
      if (eat('}')) break;
      ok = false;
    }
    return j;
  }

  Json array() {
    Json j; j.type = Json::ARR;
    ++p;  // [
    ws();
    if (eat(']')) return j;
    while (ok) {
      j.arr.push_back(parse());
      if (eat(',')) continue;
      if (eat(']')) break;
      ok = false;
    }
    return j;
  }

  Json string_() {
    Json j; j.type = Json::STR;
    ++p;  // "
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) {
        ++p;
        switch (*p) {
          case 'n': j.str += '\n'; break;
          case 't': j.str += '\t'; break;
          case 'r': j.str += '\r'; break;
          case 'b': j.str += '\b'; break;
          case 'f': j.str += '\f'; break;
          case 'u': {
            if (p + 4 < end) {
              unsigned code = strtoul(std::string(p + 1, p + 5).c_str(),
                                      nullptr, 16);
              // BMP-only UTF-8 encoding (paths/env rarely need more).
              if (code < 0x80) {
                j.str += (char)code;
              } else if (code < 0x800) {
                j.str += (char)(0xC0 | (code >> 6));
                j.str += (char)(0x80 | (code & 0x3F));
              } else {
                j.str += (char)(0xE0 | (code >> 12));
                j.str += (char)(0x80 | ((code >> 6) & 0x3F));
                j.str += (char)(0x80 | (code & 0x3F));
              }
              p += 4;
            }
            break;
          }
          default: j.str += *p;
        }
      } else {
        j.str += *p;
      }
      ++p;
    }
    if (p < end) ++p;  // closing "
    return j;
  }

  Json boolean() {
    if (*p == 't') { p += 4; return Json::B(true); }
    p += 5;
    return Json::B(false);
  }

  Json number() {
    char* q = nullptr;
    double v = strtod(p, &q);
    if (q == p) { ok = false; return {}; }
    p = q;
    return Json::N(v);
  }
};

static void dump(const Json& j, std::string& out) {
  char buf[64];
  switch (j.type) {
    case Json::NUL: out += "null"; break;
    case Json::BOOL: out += j.b ? "true" : "false"; break;
    case Json::NUM:
      if (j.num == (long long)j.num) {
        snprintf(buf, sizeof buf, "%lld", (long long)j.num);
      } else {
        snprintf(buf, sizeof buf, "%.6f", j.num);
      }
      out += buf;
      break;
    case Json::STR: {
      out += '"';
      for (char c : j.str) {
        switch (c) {
          case '"': out += "\\\""; break;
          case '\\': out += "\\\\"; break;
          case '\n': out += "\\n"; break;
          case '\t': out += "\\t"; break;
          case '\r': out += "\\r"; break;
          default:
            if ((unsigned char)c < 0x20) {
              snprintf(buf, sizeof buf, "\\u%04x", c);
              out += buf;
            } else {
              out += c;
            }
        }
      }
      out += '"';
      break;
    }
    case Json::ARR: {
      out += '[';
      for (size_t i = 0; i < j.arr.size(); ++i) {
        if (i) out += ',';
        dump(j.arr[i], out);
      }
      out += ']';
      break;
    }
    case Json::OBJ: {
      out += '{';
      bool first = true;
      for (auto& kv : j.obj) {
        if (!first) out += ',';
        first = false;
        dump(Json::S(kv.first), out);
        out += ':';
        dump(kv.second, out);
      }
      out += '}';
      break;
    }
  }
}

static std::string dumps(const Json& j) {
  std::string out;
  dump(j, out);
  return out;
}

// ---------------------------------------------------------------------------
// Supervised-task table + state file
// ---------------------------------------------------------------------------

struct Sup {
  pid_t pid = 0;
  double start_ts = 0;
  bool child = false;  // our fork (waitpid) vs recovered (poll)
  bool done = false;
  int exit_code = 0;
  int term_signal = 0;
  std::string cgroup;
};

static std::mutex g_mu;
static std::map<std::string, std::shared_ptr<Sup>> g_tasks;
static std::string g_state_path;

static double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return ts.tv_sec + ts.tv_nsec / 1e9;
}

static bool pid_alive(pid_t pid) {
  return pid > 0 && (kill(pid, 0) == 0 || errno == EPERM);
}

static void kill_group(pid_t pid, int sig) {
  if (pid <= 0) return;
  if (kill(-pid, sig) != 0) kill(pid, sig);
}

static void save_state() {
  Json root = Json::O();
  root.obj["pid"] = Json::N(getpid());
  Json tasks = Json::O();
  {
    std::lock_guard<std::mutex> lk(g_mu);
    for (auto& kv : g_tasks) {
      if (kv.second->done) continue;
      Json t = Json::O();
      t.obj["pid"] = Json::N(kv.second->pid);
      t.obj["start_ts"] = Json::N(kv.second->start_ts);
      tasks.obj[kv.first] = t;
    }
  }
  root.obj["tasks"] = tasks;
  std::string data = dumps(root);
  std::string tmp = g_state_path + ".tmp";
  FILE* fh = fopen(tmp.c_str(), "w");
  if (!fh) return;
  fwrite(data.data(), 1, data.size(), fh);
  fclose(fh);
  rename(tmp.c_str(), g_state_path.c_str());
}

static void reap_thread(std::string id, std::shared_ptr<Sup> sup) {
  if (sup->child) {
    int status = 0;
    while (waitpid(sup->pid, &status, 0) < 0 && errno == EINTR) {
    }
    if (WIFEXITED(status)) {
      sup->exit_code = WEXITSTATUS(status);
    } else if (WIFSIGNALED(status)) {
      sup->term_signal = WTERMSIG(status);
    }
  } else {
    // Recovered (reparented) task: exit status unobservable; poll.
    while (pid_alive(sup->pid)) usleep(200 * 1000);
  }
  sup->done = true;
  if (!sup->cgroup.empty()) rmdir(sup->cgroup.c_str());
  save_state();
}

// ---------------------------------------------------------------------------
// Ops
// ---------------------------------------------------------------------------

static const std::map<std::string, int> kRlimits = {
    {"cpu", RLIMIT_CPU},     {"nofile", RLIMIT_NOFILE},
    {"as", RLIMIT_AS},       {"fsize", RLIMIT_FSIZE},
    {"nproc", RLIMIT_NPROC},
};

// execve() does no PATH search: a bare argv[0] ("python3") is taken as a
// path relative to the task cwd and exits 127 even when the command is on
// the task's PATH.  Resolve it against the REQUEST env's PATH (the task's
// view of the world, which may differ from the supervisor's), falling
// back to the supervisor's own.
static std::string resolve_argv0(const std::string& cmd,
                                 const std::vector<std::string>& envs) {
  if (cmd.empty() || cmd.find('/') != std::string::npos) return cmd;
  std::string path;
  for (auto& e : envs)
    if (e.rfind("PATH=", 0) == 0) { path = e.substr(5); break; }
  if (path.empty()) {
    const char* p = getenv("PATH");
    path = p ? p : "";
  }
  size_t start = 0;
  while (start <= path.size()) {
    size_t end = path.find(':', start);
    std::string dir = end == std::string::npos
                          ? path.substr(start)
                          : path.substr(start, end - start);
    if (!dir.empty()) {
      std::string cand = dir + "/" + cmd;
      if (access(cand.c_str(), X_OK) == 0) return cand;
    }
    if (end == std::string::npos) break;
    start = end + 1;
  }
  return cmd;
}

static Json op_start(const Json& req) {
  std::string id = req.s("id");
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_tasks.find(id);
    if (it != g_tasks.end() && !it->second->done) {
      // Idempotent: a retried start must not launch a second copy.
      Json out = Json::O();
      out.obj["pid"] = Json::N(it->second->pid);
      out.obj["start_ts"] = Json::N(it->second->start_ts);
      return out;
    }
  }
  const Json& argv_j = req.at("argv");
  if (argv_j.type != Json::ARR || argv_j.arr.empty()) {
    Json e = Json::O();
    e.obj["error"] = Json::S("start requires argv");
    return e;
  }
  std::vector<std::string> argv;
  for (auto& a : argv_j.arr) argv.push_back(a.str);
  std::vector<std::string> envs;
  for (auto& kv : req.at("env").obj)
    envs.push_back(kv.first + "=" + kv.second.str);
  argv[0] = resolve_argv0(argv[0], envs);

  std::string cgroup;
  if (req.truthy("cgroup")) {
    std::string base = "/sys/fs/cgroup/nomad_tpu";
    mkdir(base.c_str(), 0755);
    cgroup = base + "/" + id;
    if (mkdir(cgroup.c_str(), 0755) != 0 && errno != EEXIST) cgroup.clear();
  }

  int devnull = open("/dev/null", O_RDONLY);
  pid_t pid = fork();
  if (pid == 0) {
    // Child: own session (group kills + survives the sidecar), rlimits,
    // redirections, then exec.
    setsid();
    for (auto& kv : req.at("rlimits").obj) {
      auto it = kRlimits.find(kv.first);
      if (it != kRlimits.end()) {
        struct rlimit rl;
        rl.rlim_cur = rl.rlim_max = (rlim_t)kv.second.num;
        setrlimit(it->second, &rl);
      }
    }
    std::string cwd = req.s("cwd");
    if (!cwd.empty() && chdir(cwd.c_str()) != 0) _exit(127);
    int out = open(req.s("stdout").c_str(), O_WRONLY | O_CREAT | O_APPEND,
                   0644);
    int err = open(req.s("stderr").c_str(), O_WRONLY | O_CREAT | O_APPEND,
                   0644);
    if (devnull >= 0) dup2(devnull, 0);
    if (out >= 0) dup2(out, 1);
    if (err >= 0) dup2(err, 2);
    std::vector<char*> cargv;
    for (auto& a : argv) cargv.push_back(const_cast<char*>(a.c_str()));
    cargv.push_back(nullptr);
    std::vector<char*> cenv;
    for (auto& e : envs) cenv.push_back(const_cast<char*>(e.c_str()));
    cenv.push_back(nullptr);
    execve(cargv[0], cargv.data(),
           envs.empty() ? environ : cenv.data());
    _exit(127);
  }
  if (devnull >= 0) close(devnull);
  if (pid < 0) {
    Json e = Json::O();
    e.obj["error"] = Json::S(std::string("fork failed: ") + strerror(errno));
    return e;
  }
  if (!cgroup.empty()) {
    std::string procs = cgroup + "/cgroup.procs";
    FILE* fh = fopen(procs.c_str(), "w");
    if (fh) {
      fprintf(fh, "%d", pid);
      fclose(fh);
    } else {
      cgroup.clear();
    }
  }
  auto sup = std::make_shared<Sup>();
  sup->pid = pid;
  sup->start_ts = now_s();
  sup->child = true;
  sup->cgroup = cgroup;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    g_tasks[id] = sup;
  }
  save_state();
  std::thread(reap_thread, id, sup).detach();
  Json out = Json::O();
  out.obj["pid"] = Json::N(pid);
  out.obj["start_ts"] = Json::N(sup->start_ts);
  return out;
}

static Json op_wait(const Json& req) {
  std::shared_ptr<Sup> sup;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_tasks.find(req.s("id"));
    if (it != g_tasks.end()) sup = it->second;
  }
  Json out = Json::O();
  if (!sup) {
    out.obj["error"] = Json::S("unknown task");
    return out;
  }
  if (!sup->done) {
    out.obj["running"] = Json::B(true);
    return out;
  }
  out.obj["exit_code"] = Json::N(sup->exit_code);
  out.obj["signal"] = Json::N(sup->term_signal);
  out.obj["recovered"] = Json::B(!sup->child);
  return out;
}

static Json op_signal(const Json& req) {
  std::shared_ptr<Sup> sup;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_tasks.find(req.s("id"));
    if (it != g_tasks.end()) sup = it->second;
  }
  Json out = Json::O();
  if (!sup || sup->done) {
    out.obj["error"] = Json::S("unknown or finished task");
    return out;
  }
  kill_group(sup->pid, (int)req.n("signal", SIGTERM));
  return Json::O();
}

static Json op_stop(const Json& req) {
  std::shared_ptr<Sup> sup;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_tasks.find(req.s("id"));
    if (it != g_tasks.end()) sup = it->second;
  }
  if (sup && !sup->done) {
    double grace = req.n("grace", 5.0);
    kill_group(sup->pid, SIGTERM);
    std::thread([sup, grace] {
      usleep((useconds_t)(grace * 1e6));
      if (!sup->done) kill_group(sup->pid, SIGKILL);
    }).detach();
  }
  return Json::O();
}

static Json op_destroy(const Json& req) {
  std::shared_ptr<Sup> sup;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_tasks.find(req.s("id"));
    if (it != g_tasks.end()) {
      sup = it->second;
      g_tasks.erase(it);
    }
  }
  if (sup && !sup->done) kill_group(sup->pid, SIGKILL);
  save_state();
  return Json::O();
}

static Json op_recover(const Json& req) {
  pid_t pid = (pid_t)req.n("pid");
  Json out = Json::O();
  if (!pid_alive(pid)) {
    out.obj["ok"] = Json::B(false);
    return out;
  }
  auto sup = std::make_shared<Sup>();
  sup->pid = pid;
  sup->start_ts = req.n("start_ts");
  sup->child = false;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    g_tasks[req.s("id")] = sup;
  }
  save_state();
  std::thread(reap_thread, req.s("id"), sup).detach();
  out.obj["ok"] = Json::B(true);
  return out;
}

static Json op_list(const Json&) {
  Json tasks = Json::O();
  std::lock_guard<std::mutex> lk(g_mu);
  for (auto& kv : g_tasks) {
    Json t = Json::O();
    t.obj["pid"] = Json::N(kv.second->pid);
    t.obj["start_ts"] = Json::N(kv.second->start_ts);
    t.obj["running"] = Json::B(!kv.second->done);
    tasks.obj[kv.first] = t;
  }
  Json out = Json::O();
  out.obj["tasks"] = tasks;
  return out;
}

// ---------------------------------------------------------------------------
// Socket server (thread per connection, newline-delimited JSON)
// ---------------------------------------------------------------------------

static void handle_conn(int fd) {
  std::string buf;
  char chunk[4096];
  for (;;) {
    ssize_t n = read(fd, chunk, sizeof chunk);
    if (n <= 0) break;
    buf.append(chunk, n);
    size_t pos;
    while ((pos = buf.find('\n')) != std::string::npos) {
      std::string line = buf.substr(0, pos);
      buf.erase(0, pos + 1);
      if (line.empty()) continue;
      Parser parser(line);
      Json req = parser.parse();
      Json out;
      std::string op = parser.ok ? req.s("op") : "";
      if (!parser.ok) {
        out = Json::O();
        out.obj["error"] = Json::S("bad json");
      } else if (op == "ping") {
        out = Json::O();
        out.obj["pong"] = Json::B(true);
        out.obj["pid"] = Json::N(getpid());
        out.obj["native"] = Json::B(true);
      } else if (op == "start") {
        out = op_start(req);
      } else if (op == "wait") {
        out = op_wait(req);
      } else if (op == "stop") {
        out = op_stop(req);
      } else if (op == "signal") {
        out = op_signal(req);
      } else if (op == "destroy") {
        out = op_destroy(req);
      } else if (op == "recover") {
        out = op_recover(req);
      } else if (op == "list") {
        out = op_list(req);
      } else if (op == "shutdown") {
        std::string resp = "{}\n";
        (void)!write(fd, resp.data(), resp.size());
        _exit(0);
      } else {
        out = Json::O();
        out.obj["error"] = Json::S("bad op '" + op + "'");
      }
      std::string resp = dumps(out) + "\n";
      if (write(fd, resp.data(), resp.size()) < 0) break;
    }
  }
  close(fd);
}

int main(int argc, char** argv) {
  std::string sock_path, state_dir;
  for (int i = 1; i + 1 < argc; i += 2) {
    if (!strcmp(argv[i], "--socket")) sock_path = argv[i + 1];
    if (!strcmp(argv[i], "--state-dir")) state_dir = argv[i + 1];
  }
  if (sock_path.empty() || state_dir.empty()) {
    fprintf(stderr, "usage: %s --socket PATH --state-dir DIR\n", argv[0]);
    return 2;
  }
  signal(SIGPIPE, SIG_IGN);
  mkdir(state_dir.c_str(), 0755);
  g_state_path = state_dir + "/executor.state.json";
  save_state();  // truncate: this sidecar's own (empty) table

  unlink(sock_path.c_str());
  int sfd = socket(AF_UNIX, SOCK_STREAM, 0);
  if (sfd < 0) return 1;
  struct sockaddr_un addr;
  memset(&addr, 0, sizeof addr);
  addr.sun_family = AF_UNIX;
  strncpy(addr.sun_path, sock_path.c_str(), sizeof addr.sun_path - 1);
  if (bind(sfd, (struct sockaddr*)&addr, sizeof addr) != 0) return 1;
  if (listen(sfd, 64) != 0) return 1;
  for (;;) {
    int fd = accept(sfd, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EINTR) continue;
      break;
    }
    std::thread(handle_conn, fd).detach();
  }
  return 0;
}
